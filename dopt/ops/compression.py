"""Compression operators for communication-efficient gossip (CHOCO-SGD).

The reference has no notion of communication cost at all (its "network"
is Python object passing — SURVEY §2.4); these operators exist for the
framework's own communication-efficient algorithms
(``GossipConfig.algorithm='choco'``): each worker communicates a
compressed *difference* ``Q(x_i − x̂_i)`` instead of full parameters,
with the error kept in ``x_i − x̂_i`` and fed back next round (error
feedback is what makes aggressive compression convergent).

All operators are pure, shape-static (XLA-friendly: ``top_k`` with a
compile-time k, seeded masks instead of data-dependent sparsity), and
act per worker on stacked [W, ...] pytrees.

Contract: an operator maps (tree, key) → tree of the same structure.
For the SPARSIFIERS (``topk``, ``randk``) ``ratio`` is the fraction of
entries communicated and ``ratio=1.0`` is the exact identity — that
invariant is what the choco≡dsgd reduction test pins.  ``qsgd`` is a
QUANTIZER with different ratio semantics: ratio sets the level count
(ratio=1 → 256-level stochastic quantization, NOT the identity); use
``compression='none'`` for the exact D-SGD reduction.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _per_worker_topk(flat: jnp.ndarray, k: int) -> jnp.ndarray:
    """flat: [W, N] — keep the k largest-|·| entries per row."""
    n = flat.shape[1]
    if k >= n:
        return flat
    _, idx = jax.lax.top_k(jnp.abs(flat), k)          # [W, k]
    mask = jnp.zeros_like(flat).at[
        jnp.arange(flat.shape[0])[:, None], idx].set(1.0)
    return flat * mask


def top_k_compress(tree, ratio: float):
    """Magnitude top-k sparsification, per worker per leaf.  k is
    static: ceil(ratio · leaf_size) — jit-stable shapes."""
    if ratio >= 1.0:
        return tree

    def comp(x):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n).astype(jnp.float32)
        return _per_worker_topk(flat, k).reshape(x.shape).astype(x.dtype)

    return jax.tree.map(comp, tree)


def rand_k_compress(tree, ratio: float, key):
    """Random-k sparsification with 1/ratio rescaling (unbiased).  The
    mask is drawn from ``key`` per leaf — pass a per-round key so
    workers/rounds decorrelate."""
    if ratio >= 1.0:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))

    def comp(x, k):
        mask = (jax.random.uniform(k, x.shape) < ratio).astype(x.dtype)
        return x * mask / jnp.asarray(ratio, x.dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def qsgd_compress(tree, ratio: float, key, *, bucket_size: int = 2048):
    """QSGD stochastic quantization (Alistarh et al. 2017), per worker
    per leaf: x → ‖x‖₂ · sign(x) · ξ(x)/s with ξ an unbiased stochastic
    rounding of s·|x|/‖x‖₂ to integer levels.  ``ratio`` sets the level
    count s = max(round(ratio · 256), 1) — the fraction of an 8-bit
    range used; smaller ratio = coarser quantization = fewer wire bits
    in a real packed transport.

    Norms are per ``bucket_size`` chunk (standard QSGD bucketing):
    without it the quantization step scales with the WHOLE leaf's norm
    (~√N · rms) and the noise swamps million-parameter models."""
    s = max(int(round(ratio * 256)), 1)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))

    def comp(x, k):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        b = min(bucket_size, n)
        nb = -(-n // b)
        pad = nb * b - n
        flat = x.reshape(w, n).astype(jnp.float32)
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, pad)))
        bk = flat.reshape(w, nb, b)
        norm = jnp.linalg.norm(bk, axis=2, keepdims=True)
        safe = jnp.maximum(norm, 1e-12)
        level = s * jnp.abs(bk) / safe                     # in [0, s]
        floor = jnp.floor(level)
        frac = level - floor
        up = (jax.random.uniform(k, bk.shape) < frac).astype(jnp.float32)
        q = jnp.sign(bk) * (floor + up) * safe / s
        q = jnp.where(norm > 0, q, 0.0)
        q = q.reshape(w, nb * b)[:, :n]
        return q.reshape(x.shape).astype(x.dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def make_compressor(name: str, ratio: float):
    """Operator factory: (tree, key) → compressed tree.

    'topk'  — deterministic magnitude top-k (ignores the key)
    'randk' — unbiased random-k with rescaling
    'qsgd'  — unbiased stochastic quantization (ratio sets level count)
    'none'  — identity (ratio ignored)
    """
    if name not in ("none", "topk", "randk", "qsgd"):
        raise ValueError(
            f"unknown compressor {name!r}; one of none|topk|randk|qsgd")
    if name != "none" and not 0.0 < ratio <= 1.0:
        # ratio=0 would divide by zero in randk (NaN params on round 0)
        # and negative ratios would silently zero all communication.
        raise ValueError(f"compression_ratio must be in (0, 1], got {ratio}")
    if name == "none" or (name != "qsgd" and ratio >= 1.0):
        return lambda tree, key: tree
    if name == "topk":
        return lambda tree, key: top_k_compress(tree, ratio)
    if name == "qsgd":
        return lambda tree, key: qsgd_compress(tree, ratio, key)
    return lambda tree, key: rand_k_compress(tree, ratio, key)
