"""Per-layer grouped-conv roofline for baseline5 (VERDICT r4 item 5).

For every distinct conv shape in the grouped-stacked ResNet-18 fleet
program (32 workers as feature_group_count=32), measures achieved
training TFLOP/s (fwd + bwd, 3x fwd accounting matched by actual
autodiff work) two ways on the real chip:

* grouped   — the fleet execution: x [B, H, W, 32*Cin], kernel
              [kh, kw, Cin, 32*Cout], feature_group_count=32.
* single    — the fleet-INDEPENDENCE bound term: one weight set at the
              same total sample count: x [32*B, H, W, Cin] (groups=1).

The ratio column shows exactly which layers pay a grouped-conv penalty
and which hit the same hardware ceiling either way — the committed
evidence behind roofline_baseline5.json's measured_fraction_of_bound.
Also probes the two worst layers with lane-batch 128 (local_bs
128/lane, VERDICT's suggested recovery lever).

Writes results/roofline_layers_baseline5.json.
Usage: python scripts/roofline_layers.py [--iters 30]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Per-preset fleet geometry: workers (feature groups), per-lane batch,
# and the distinct conv shapes (name, count, H, Cin, Cout, kh, stride;
# input spatial HxH).
PRESETS = {
    # baseline5: ResNet-18 stage structure at 32x32 CIFAR inputs
    # (stage_sizes (2,2,2,2)).
    "baseline5": {
        "workers": 32, "lane_batch": 64,
        "layers": [
            ("stem",        1, 32,   3,  64, 3, 1),
            ("s0.conv",     4, 32,  64,  64, 3, 1),
            ("s1.down",     1, 32,  64, 128, 3, 2),
            ("s1.conv",     3, 16, 128, 128, 3, 1),
            ("s1.proj",     1, 32,  64, 128, 1, 2),
            ("s2.down",     1, 16, 128, 256, 3, 2),
            ("s2.conv",     3,  8, 256, 256, 3, 1),
            ("s2.proj",     1, 16, 128, 256, 1, 2),
            ("s3.down",     1,  8, 256, 512, 3, 2),
            ("s3.conv",     3,  4, 512, 512, 3, 1),
            ("s3.proj",     1,  8, 256, 512, 1, 2),
        ],
    },
    # headline: bench.py's Model1 (fc layers as VALID convs, exactly the
    # grouped-stacked program's shapes).  conv1 is the documented sore
    # spot: 1 input channel per group — every formulation tried (direct,
    # grouped-1x1-over-patches, batched einsum) lands within ~10% of the
    # same cost; the time is activation-layout movement, not math.
    "headline": {
        "workers": 6, "lane_batch": 128,
        "layers": [
            ("conv1",  1, 28,   1,  32, 5, 1),
            ("conv2",  1, 14,  32,  64, 5, 1),
            ("fc1",    1,  7,  64, 512, 7, 1),   # VALID 7x7 -> 1x1
            ("fc2",    1,  1, 512,  10, 1, 1),
        ],
    },
}

W = 32          # set per-preset in main()
B = 64


def conv_flops(h, cin, cout, k, stride, batch, groups, pad="SAME"):
    ho = h // stride if pad == "SAME" else h - k + 1
    macs = batch * ho * ho * cout * k * k * cin * groups
    return 2 * macs          # fwd FLOPs; training = 3x (fwd+bwd)


def _device_seconds(blk) -> float:
    """Profiler device self-time of ``blk()`` in seconds.  Roofline
    numbers are committed artifacts, so a degraded profiler stack
    (which ``device_stats_of`` tolerates for bench) must fail LOUDLY
    here — NaN-derived TFLOP/s in the JSON would be worse than no run."""
    from dopt.utils.profiling import device_stats_of

    stats = device_stats_of(blk)
    if "warning" in stats:
        raise RuntimeError(
            "roofline needs the profiler device-time basis but it "
            f"degraded: {stats['warning']}")
    return stats["device_self_time_us"] / 1e6


def measure(fn, args, iters):
    """Per-iteration time of fwd + dK + dX (the full 3x-fwd training
    cost the table's FLOP accounting assumes), measured as ONE jitted
    ``lax.scan`` of ``iters`` DEPENDENT steps — each step feeds its
    gradients back into the next step's inputs, so no iteration can be
    elided, reordered, or overlapped (a naive dispatch loop over a
    remote-tunnel device measured impossible >10 PFLOP/s)."""
    import jax

    def run_impl(k, x, ct):
        # ct enters as a jit ARGUMENT (a closure constant this large
        # blows the remote-compile request-size limit).
        def body(carry, _):
            k_, x_ = carry
            dk, dx = jax.grad(fn, argnums=(0, 1))(k_, x_, ct)
            return (k_ + 1e-4 * dk, x_ + 1e-4 * dx), ()

        return jax.lax.scan(body, (k, x), None, length=iters)[0]

    run = jax.jit(run_impl)
    r = run(*args)
    jax.block_until_ready(r)
    # Wall-clock is NOT trustworthy on this tunneled device for
    # sub-second intervals (block_until_ready returns early; a naive
    # loop measured >40 PFLOP/s on a 197 TF/s chip).  The profiler's
    # device self-time is repeatable to ~0.01% and is the basis here.
    def blk():
        jax.block_until_ready(run(*args))

    return _device_seconds(blk) / iters


def bench_layer(h, cin, cout, k, stride, *, workers=W, lane_batch=B,
                iters=30, pad="SAME"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    W_ = workers
    rng = np.random.default_rng(0)
    ho = h // stride if pad == "SAME" else h - k + 1
    kern_g = jnp.asarray(rng.normal(size=(k, k, cin, W_ * cout)) * 0.05,
                         jnp.bfloat16)
    x_g = jnp.asarray(rng.normal(size=(lane_batch, h, h, W_ * cin)),
                      jnp.bfloat16)
    # Random fixed cotangent: with a plain sum loss the cotangent is
    # all-ones and XLA legally simplifies BOTH backward convolutions to
    # cheap reductions (measured >chip-peak "TFLOP/s"); a random c
    # keeps dX and dK honest full convolutions.
    c_g = jnp.asarray(rng.normal(size=(lane_batch, ho, ho, W_ * cout)),
                      jnp.bfloat16)

    def f_grouped(kern, x, ct):
        out = jax.lax.conv_general_dilated(
            x, kern, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=W_)
        return jnp.sum((out * ct).astype(jnp.float32))

    kern_s = jnp.asarray(rng.normal(size=(k, k, cin, cout)) * 0.05,
                         jnp.bfloat16)
    x_s = jnp.asarray(rng.normal(size=(W_ * lane_batch, h, h, cin)),
                      jnp.bfloat16)
    c_s = jnp.asarray(rng.normal(size=(W_ * lane_batch, ho, ho, cout)),
                      jnp.bfloat16)

    def f_single(kern, x, ct):
        out = jax.lax.conv_general_dilated(
            x, kern, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum((out * ct).astype(jnp.float32))

    t_g = measure(f_grouped, (kern_g, x_g, c_g), iters)
    t_s = measure(f_single, (kern_s, x_s, c_s), iters)
    fl = 3 * conv_flops(h, cin, cout, k, stride, lane_batch, W_, pad)
    return fl, fl / t_g / 1e12, fl / t_s / 1e12


def bench_update(params_total, iters, *, lr=0.01, mu=0.5):
    """Device time per momentum-SGD update of a ``params_total``-element
    fleet parameter vector (the weight-update phase: 3 reads, 2 writes,
    zero FLOP reuse — pure HBM bandwidth), measured as one jitted scan
    of DEPENDENT steps exactly like ``measure``.  This is the
    non-conv round fraction ISSUE 5 shards away (update_sharding=
    "scatter" runs it on 1/D of the flat tree), committed here so
    regressions in the update share are attributable from the
    artifact."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(1)
    p = jnp.asarray(rng.normal(size=params_total).astype(np.float32))
    m = jnp.zeros_like(p)
    g = jnp.asarray(rng.normal(size=params_total).astype(np.float32))

    def run_impl(p0, m0, gg):
        def body(carry, _):
            p_, m_ = carry
            buf = mu * m_ + gg
            return (p_ - lr * buf, buf), ()

        return jax.lax.scan(body, (p0, m0), None, length=iters)[0]

    run = jax.jit(run_impl)
    jax.block_until_ready(run(p, m, g))

    def blk():
        jax.block_until_ready(run(p, m, g))

    return _device_seconds(blk) / iters


def fleet_param_count(geom) -> int:
    """Conv-layer fleet parameter count for a preset's geometry table
    (weights + biases, × workers).  Exact for the headline Model1
    (1.66M × 6); for baseline5 it covers the conv stack the table
    describes (the norm/fc tail is <1% of the ResNet tree)."""
    per_worker = sum(count * (k * k * cin * cout + cout)
                     for _, count, _, cin, cout, k, _ in geom["layers"])
    return geom["workers"] * per_worker


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--preset", default="baseline5",
                    choices=sorted(PRESETS))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    geom = PRESETS[args.preset]
    workers, lane_b = geom["workers"], geom["lane_batch"]
    out_path = (args.out
                or f"results/roofline_layers_{args.preset}.json")

    import jax

    from dopt.utils.profiling import device_peak_flops

    kind, peak = device_peak_flops()
    rows = []
    for name, count, h, cin, cout, k, stride in geom["layers"]:
        pad = "VALID" if name.startswith("fc") else "SAME"
        fl, tf_g, tf_s = bench_layer(h, cin, cout, k, stride,
                                     workers=workers, lane_batch=lane_b,
                                     iters=args.iters, pad=pad)
        rows.append({
            "layer": name, "count": count, "spatial": h,
            "cin": cin, "cout": cout, "kernel": k, "stride": stride,
            "train_flops_fleet": fl,
            "grouped_tflops": round(tf_g, 2),
            "single_tflops": round(tf_s, 2),
            "grouped_over_single": round(tf_g / tf_s, 3),
            "grouped_mfu": round(tf_g * 1e12 / peak, 4) if peak else None,
        })
        print(f"{name:10s} {h:3}px {cin:4}->{cout:<4} k{k} s{stride}: "
              f"grouped {tf_g:6.1f} TF/s, single {tf_s:6.1f} TF/s "
              f"(ratio {tf_g/tf_s:.2f})", flush=True)

    # Weighted fleet summary: time-weighted by per-layer grouped cost.
    tot_fl = sum(r["train_flops_fleet"] * r["count"] for r in rows)
    tot_tg = sum(r["train_flops_fleet"] * r["count"]
                 / (r["grouped_tflops"] * 1e12) for r in rows)
    tot_ts = sum(r["train_flops_fleet"] * r["count"]
                 / (r["single_tflops"] * 1e12) for r in rows)
    summary = {
        "conv_stack_grouped_tflops": round(tot_fl / tot_tg / 1e12, 2),
        "conv_stack_single_tflops": round(tot_fl / tot_ts / 1e12, 2),
        "conv_stack_grouped_fraction_of_single": round(tot_ts / tot_tg, 3),
    }
    print("conv stack:", summary, flush=True)

    # Recovery probe: the two worst ratio layers at 2x the lane batch
    # (the local_bs lever).
    probes = []
    if lane_b < 128:
        worst = sorted(rows, key=lambda r: r["grouped_over_single"])[:2]
        for r in worst:
            fl, tf_g, tf_s = bench_layer(
                r["spatial"], r["cin"], r["cout"], r["kernel"],
                r["stride"], workers=workers, lane_batch=2 * lane_b,
                iters=args.iters,
                pad=("VALID" if r["layer"].startswith("fc") else "SAME"))
            probes.append({"layer": r["layer"], "lane_batch": 2 * lane_b,
                           "grouped_tflops": round(tf_g, 2),
                           "single_tflops": round(tf_s, 2),
                           "grouped_over_single": round(tf_g / tf_s, 3)})
            print(f"probe {r['layer']} @ lane_batch={2*lane_b}: grouped "
                  f"{tf_g:.1f} single {tf_s:.1f} "
                  f"(ratio {tf_g/tf_s:.2f})", flush=True)

    # Update-phase share (ISSUE 5 satellite): the per-step weight
    # update over the full fleet tree, alongside the per-layer conv
    # compute — the committed artifact that makes regressions in the
    # NON-conv round fraction attributable.  Per-step share equals
    # per-round share (both scale with step count).
    fleet_params = fleet_param_count(geom)
    upd_s = bench_update(fleet_params, args.iters)
    conv_s = sum(r["train_flops_fleet"] * r["count"]
                 / (r["grouped_tflops"] * 1e12) for r in rows)
    update_phase = {
        "fleet_params": fleet_params,
        "update_us_per_step": round(upd_s * 1e6, 2),
        "conv_us_per_step": round(conv_s * 1e6, 2),
        "update_share_of_step": round(upd_s / (upd_s + conv_s), 4),
        "update_gbps": round(5 * 4 * fleet_params / upd_s / 1e9, 1),
        "note": ("momentum-SGD update of the fleet tree (3 reads + 2 "
                 "writes per element, dependent-step scan, profiler "
                 "device self-time) vs the conv stack's per-step time "
                 "from the table above; update_sharding='scatter' "
                 "divides the update work by the mesh size"),
    }
    print(f"update phase: {upd_s*1e6:.1f} us/step over "
          f"{fleet_params/1e6:.2f}M params "
          f"({update_phase['update_share_of_step']*100:.1f}% of "
          f"conv+update step time)", flush=True)

    payload = {
        "suite": f"roofline_layers_{args.preset}",
        "device": str(jax.devices()[0]),
        "device_kind": kind,
        "bf16_peak_tflops": peak / 1e12 if peak else None,
        "workers": workers, "lane_batch": lane_b,
        "note": ("fwd+dK+dX achieved TFLOP/s per distinct conv shape "
                 "(dependent-step scan, random cotangent, profiler "
                 "device self-time); 'single' = one weight set at the "
                 "same total sample count (the fleet-independence "
                 "bound term).  SAME-padding FLOPs are nominal "
                 "k^2*Cin*H'*W' — XLA skips padded taps, so small-"
                 "spatial rows overstate achieved TFLOP/s by up to "
                 "~1.4x; the grouped/single ratio cancels that."),
        "layers": rows,
        "summary": summary,
        "update_phase": update_phase,
        "double_lane_batch_probe": probes,
    }
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
