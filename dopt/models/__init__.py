from dopt.models.zoo import (
    MLP,
    LogisticRegression,
    Model1,
    Model3,
    ResNet18,
    build_model,
    count_params,
)
from dopt.models.losses import cross_entropy, accuracy

__all__ = [
    "MLP",
    "LogisticRegression",
    "Model1",
    "Model3",
    "ResNet18",
    "build_model",
    "count_params",
    "cross_entropy",
    "accuracy",
]
