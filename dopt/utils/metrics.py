"""Structured metrics sink (the reference's ``history`` pattern, typed).

Every reference orchestrator appends per-round dicts to ``history``
(``servers.py:77``, ``simulators.py:99-108``) and the notebooks dump
them to CSV (``results/*.csv``, columns
``round, avg_test_acc, avg_test_loss, avg_train_loss``).  ``History``
is one sink with both schemas: P1 federated
(round, test_acc, test_loss, train_loss, train_acc) and P2 gossip
(round, avg_test_acc, avg_test_loss, avg_train_loss); CSV export is
byte-compatible with the committed result files' column layout.
"""

from __future__ import annotations

import csv
import io
import json
import os
from pathlib import Path
from typing import Any, Iterator


def atomic_write_text(path: str | Path, text: str,
                      newline: str | None = None) -> Path:
    """Crash-safe file write: materialise into a same-directory temp
    file, then ``os.replace`` into place (atomic on POSIX).  A process
    killed mid-write leaves either the previous complete file or
    nothing — never a truncated artifact — matching the size-manifest
    hardening of ``dopt.utils.checkpoint``.  All History exports
    (results CSV/JSON, the ``--faults-json`` ledger) go through here.
    ``newline`` passes through to the write (the csv module's content
    carries its own ``\\r\\n`` terminators — pass ``""`` to keep them
    byte-exact instead of letting text mode re-translate)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f".{path.name}.tmp{os.getpid()}")
    try:
        tmp.write_text(text, newline=newline)
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


class History:
    """Append-only per-round record store with CSV/JSON export."""

    def __init__(self, name: str = "history"):
        self.name = name
        self.rows: list[dict[str, Any]] = []
        # Fault ledger (dopt.faults): one row per injected fault —
        # {round, worker, kind, action} — so faulted runs are auditable
        # and replayable.  Appended by the engines as faults are
        # injected, checkpointed alongside ``rows``.
        self.faults: list[dict[str, Any]] = []

    def append(self, **row: Any) -> None:
        self.rows.append({k: _scalar(v) for k, v in row.items()})

    def log_fault(self, *, round: int, worker: int, kind: str,
                  action: str) -> None:
        """Record one injected fault in the ledger (schema: round,
        worker, kind ∈ dopt.faults.KINDS, action taken)."""
        self.faults.append({"round": int(round), "worker": int(worker),
                            "kind": str(kind), "action": str(action)})

    def faults_to_json(self, path: str | Path) -> Path:
        return atomic_write_text(path, json.dumps(self.faults, indent=2))

    @staticmethod
    def faults_from_json(path: str | Path) -> list[dict[str, Any]]:
        """Re-load a ``--faults-json`` export.  Round-trips the in-
        ``History`` ledger row-for-row (the schema is plain
        int/str scalars), so exported traces stay audit-complete —
        pinned by tests/test_network.py's round-trip test."""
        with open(path) as f:
            rows = json.load(f)
        if not isinstance(rows, list) or any(
                not isinstance(r, dict) for r in rows):
            raise ValueError(f"{path}: not a fault-ledger export "
                             "(expected a JSON list of row objects)")
        return rows

    def __len__(self) -> int:
        return len(self.rows)

    def __iter__(self) -> Iterator[dict[str, Any]]:
        return iter(self.rows)

    def __getitem__(self, key: str) -> list[Any]:
        """Column access: history['avg_test_acc'] -> list over rounds."""
        return [r.get(key) for r in self.rows]

    def last(self) -> dict[str, Any]:
        return self.rows[-1] if self.rows else {}

    # Reference results/*.csv column orders (P2 ``history`` dumps:
    # round, avg_test_acc, avg_test_loss, avg_train_loss; P1 ``history``:
    # round, test_acc, test_loss, train_loss, train_acc) — shared columns
    # are emitted in this order so dopt CSVs diff cleanly against the
    # reference's committed files; extra columns follow in first-seen
    # order.
    _CSV_ORDER = ("round", "avg_test_acc", "avg_test_loss",
                  "avg_train_loss", "test_acc", "test_loss", "train_loss",
                  "train_acc")

    def to_csv(self, path: str | Path) -> Path:
        """Write rows in the reference results/*.csv layout (leading
        unnamed index column, then the columns — union over ALL rows,
        since non-eval rounds carry fewer keys than eval rounds).
        Written atomically (``atomic_write_text``)."""
        seen: dict[str, None] = {}
        for r in self.rows:
            for k in r:
                seen.setdefault(k)
        cols = [c for c in self._CSV_ORDER if c in seen]
        cols += [c for c in seen if c not in cols]
        buf = io.StringIO()
        w = csv.writer(buf)
        w.writerow([""] + cols)
        for i, r in enumerate(self.rows):
            w.writerow([i] + [r.get(c, "") for c in cols])
        return atomic_write_text(path, buf.getvalue(), newline="")

    def to_json(self, path: str | Path) -> Path:
        return atomic_write_text(path, json.dumps(self.rows, indent=2))

    @classmethod
    def from_csv(cls, path: str | Path, name: str = "history") -> "History":
        h = cls(name)
        with open(path, newline="") as f:
            reader = csv.DictReader(f)
            for row in reader:
                # Blank cells are ABSENT keys, not empty strings: the
                # CSV layout unions heterogeneous row schemas (non-eval
                # rounds carry fewer keys than eval rounds) and fills
                # the gaps with "", so the round trip must drop them to
                # recover the original row shapes.
                h.rows.append({
                    k: _maybe_num(v) for k, v in row.items()
                    if k not in ("", None) and v != ""
                })
        return h

    def merge_resumed(self, rows, *, key: str = "round") -> int:
        """Fold per-round rows from a RESUMED run into this history,
        enforcing the same monotonic round watermark the telemetry
        resume path uses (dopt.obs): rows at rounds this history
        already holds are dropped (the continuous prefix wins — no
        duplicates), and the first genuinely new row must CONTINUE the
        sequence (a gap raises — a missing round means the resume lost
        data).  Returns the number of rows appended."""
        last = -1
        for r in self.rows:
            if key in r and isinstance(r[key], int):
                last = max(last, r[key])
        appended = 0
        for r in rows:
            t = r.get(key)
            if not isinstance(t, int):
                raise ValueError(
                    f"merge_resumed: row without an int {key!r}: {r!r}")
            if t <= last:
                continue
            if t != last + 1:
                raise ValueError(
                    f"merge_resumed: round gap {last} -> {t} (the resumed "
                    "stream is missing rounds)")
            self.rows.append(dict(r))
            last = t
            appended += 1
        return appended


def time_to_target(history: "History", *, target: float,
                   key: str = "avg_test_acc",
                   seconds_per_round: float | None = None) -> dict[str, Any]:
    """The north-star meter (BASELINE.json): first round at which
    ``key`` reaches ``target``, and — given a measured per-round
    wall-clock — the implied time-to-target.

    Returns {reached, round, rounds, seconds} where ``round`` is the
    history row's round number, ``rounds`` counts rows up to and
    including it, and ``seconds`` is rounds * seconds_per_round (None
    when no rate is supplied).  Rows without ``key`` (eval-skipped
    rounds) are passed over.
    """
    for i, row in enumerate(history.rows):
        v = row.get(key)
        if v is not None and v >= target:
            rounds = i + 1
            return {
                "reached": True,
                "round": row.get("round", i),
                "rounds": rounds,
                "seconds": (None if seconds_per_round is None
                            else rounds * seconds_per_round),
            }
    return {"reached": False, "round": None, "rounds": None, "seconds": None}


def _scalar(v: Any) -> Any:
    """Unwrap 0-d arrays / jax scalars so rows are plain JSON-able."""
    try:
        import numpy as np
        if hasattr(v, "item") and getattr(v, "ndim", None) in (0, None):
            return v.item()
        if isinstance(v, np.generic):
            return v.item()
    except Exception:
        pass
    return v


def _maybe_num(v: str) -> Any:
    try:
        f = float(v)
        return int(f) if f.is_integer() and "." not in v else f
    except (TypeError, ValueError):
        return v


def trimmed_stats(values) -> tuple[float, float, list[float]]:
    """Outlier-hardened reduction of per-window throughput samples
    (shared by bench.py and scripts/bench_seqlm.py): with >= 4 samples
    the min and max are DISCARDED (tunneled chips throw occasional
    multi-second stalls that poison a plain max−min spread), then
    (median, spread_pct, kept) over the survivors; spread_pct =
    (max−min)/median·100 of the kept set."""
    import statistics

    vals = sorted(float(v) for v in values)
    kept = vals[1:-1] if len(vals) >= 4 else vals
    med = statistics.median(kept)
    spread = 100.0 * (kept[-1] - kept[0]) / med if med > 0 else 0.0
    return med, spread, kept
