from dopt.models.zoo import (
    MLP,
    LogisticRegression,
    Model1,
    Model3,
    ResNet18,
    build_model,
    count_params,
    make_stacked_apply,
)
from dopt.models.losses import cross_entropy, accuracy

__all__ = [
    "MLP",
    "LogisticRegression",
    "Model1",
    "Model3",
    "ResNet18",
    "build_model",
    "count_params",
    "make_stacked_apply",
    "cross_entropy",
    "accuracy",
]
