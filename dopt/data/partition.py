"""IID / non-IID data partitioning across workers.

Generalises the reference's two partitioner families into one pair:

* ``iid_split`` — random equal split without replacement
  (``Distributed Optimization/src/sampling.py:3-9``; P1's
  ``mnist_iid``/``cifar_iid``, ``Decentralized Optimization/src/sampling.py:5-12,42-49``).
* ``noniid_split`` — sort-by-label sharding, ``shards`` shards per user
  (``Distributed Optimization/src/sampling.py:11-28``; subsumes P1's
  hardcoded per-``num_users`` shard tables, sampling.py:15-39).

Outputs are both the reference-shaped ``{user: index array}`` dict and a
dense ``[num_users, shard_len]`` int32 matrix (equal-length via
truncation-to-min or pad-by-wraparound) — the form the TPU pipeline
consumes (SURVEY §3.3 TPU mapping).
"""

from __future__ import annotations

import numpy as np


def iid_split(labels: np.ndarray, num_users: int, *, seed: int = 0) -> dict[int, np.ndarray]:
    """Random equal split; every sample used at most once."""
    n = len(labels)
    per_user = n // num_users
    if per_user < 1:
        raise ValueError(f"cannot split {n} samples across {num_users} users")
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    return {
        i: np.sort(perm[i * per_user:(i + 1) * per_user]).astype(np.int64)
        for i in range(num_users)
    }


def noniid_split(
    labels: np.ndarray,
    num_users: int,
    *,
    shards_per_user: int = 2,
    seed: int = 0,
) -> dict[int, np.ndarray]:
    """Pathological non-IID: sort by label, carve into
    ``num_users * shards_per_user`` contiguous shards, deal
    ``shards_per_user`` random shards to each user — each user then sees
    ~``shards_per_user`` classes only."""
    n = len(labels)
    num_shards = num_users * shards_per_user
    shard_len = n // num_shards
    if shard_len < 1:
        raise ValueError(
            f"cannot carve {n} samples into {num_shards} shards "
            f"({num_users} users x {shards_per_user} shards)"
        )
    order = np.argsort(labels, kind="stable")
    rng = np.random.default_rng(seed)
    shard_ids = rng.permutation(num_shards)
    out: dict[int, np.ndarray] = {}
    for i in range(num_users):
        mine = shard_ids[i * shards_per_user:(i + 1) * shards_per_user]
        idx = np.concatenate([
            order[s * shard_len:(s + 1) * shard_len] for s in mine
        ])
        out[i] = np.sort(idx).astype(np.int64)
    return out


def holdout_split(
    index_matrix: np.ndarray,
    *,
    fraction: float = 0.1,
    mode: str = "deterministic",
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-worker local train/val holdout (the reference's
    ``train_val_test``): ``val_size = max(int(L * fraction), 1)`` samples
    of each worker's shard become local validation, the rest is the
    training set.

    mode='deterministic' takes the FIRST ``val_size`` indices of the
    (sorted) shard — P1's ``idxs_train = idxs[val_size:]`` /
    ``idxs_test = idxs[:val_size]`` (``Decentralized Optimization/src/
    clients.py:25-28``).  mode='random' draws the val set without
    replacement from a per-worker seeded stream — P2's
    ``np.random.choice(list(idxs), val_size)`` (``Distributed
    Optimization/src/clients.py:20-22``; the reference uses the global
    numpy RNG seeded by ``setup_seed`` — here the stream is keyed by
    (seed, worker) so the split is independent of construction order).

    Returns ``(train_matrix [W, L - val_size], val_matrix [W, val_size])``;
    rows stay sorted, and every worker's split has identical shape (the
    input rows are equal length by construction).
    """
    if not 0.0 < fraction < 1.0:
        raise ValueError(f"holdout fraction must be in (0, 1), got {fraction}")
    if mode not in ("deterministic", "random"):
        raise ValueError(
            f"unknown holdout_mode {mode!r}; one of deterministic|random")
    w, l = index_matrix.shape
    val_size = max(int(l * fraction), 1)
    if val_size >= l:
        raise ValueError(
            f"holdout of {val_size} samples leaves no training data "
            f"(shard length {l})")
    if mode == "deterministic":
        return index_matrix[:, val_size:].copy(), index_matrix[:, :val_size].copy()
    train = np.empty((w, l - val_size), dtype=index_matrix.dtype)
    val = np.empty((w, val_size), dtype=index_matrix.dtype)
    for i in range(w):
        rng = np.random.default_rng(np.random.SeedSequence([seed, 77_000 + i]))
        pos = rng.choice(l, val_size, replace=False)
        mask = np.zeros(l, dtype=bool)
        mask[pos] = True
        val[i] = np.sort(index_matrix[i][mask])
        train[i] = np.sort(index_matrix[i][~mask])
    return train, val


def partition(
    labels: np.ndarray,
    num_users: int,
    *,
    iid: bool = True,
    shards_per_user: int = 2,
    seed: int = 0,
) -> tuple[dict[int, np.ndarray], np.ndarray]:
    """Partition + dense matrix form.

    Returns ``(user_groups, index_matrix)`` where ``index_matrix`` is
    [num_users, L] with L = min user shard length (sizes are equal for
    both splitters by construction, so nothing is dropped in practice).
    """
    groups = (
        iid_split(labels, num_users, seed=seed)
        if iid
        else noniid_split(labels, num_users, shards_per_user=shards_per_user, seed=seed)
    )
    lmin = min(len(v) for v in groups.values())
    matrix = np.stack([groups[i][:lmin] for i in range(num_users)]).astype(np.int32)
    return groups, matrix


def assign_client_shards(population: int, num_shards: int, *,
                         seed: int = 0,
                         mode: str = "round_robin") -> np.ndarray:
    """Population-sized shard assignment (``dopt.population``): map each
    of ``population`` client ids onto one of the ``num_shards`` data
    shards the partitioners produced.

    mode='round_robin' — client c trains shard c % num_shards: exactly
    balanced, and when ``population == num_shards`` it is the identity
    map (client c IS shard c), which is what makes the cohort-vs-flat
    parity contract statable at all.  mode='random' — a seeded
    permutation of the round-robin assignment: still balanced to within
    one client per shard, but which clients share a shard is
    randomised (the realistic regime where clients arrive in no
    particular order).  Returns an int32 [population] vector."""
    if population < 1:
        raise ValueError(f"population must be >= 1, got {population}")
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    base = (np.arange(population) % num_shards).astype(np.int32)
    if mode == "round_robin":
        return base
    if mode == "random":
        rng = np.random.default_rng(np.random.SeedSequence([seed, 0x5A4D]))
        return base[rng.permutation(population)].astype(np.int32)
    raise ValueError(
        f"unknown client-shard assignment mode {mode!r}; "
        "one of round_robin|random")


def orphan_shard_adopters(assignment: np.ndarray, alive: np.ndarray,
                          num_shards: int) -> dict[int, int]:
    """Shard-reassignment map for population churn: a shard whose
    ASSIGNED clients are all away this round is orphaned — no sampled
    cohort could ever train it — so it is adopted by the next shard id
    (mod S) that still has an alive client, and ``reassign_shards``
    interleaves the orphan's rows into the adopter's for the round.
    The mirror of ``FaultPlan.adopters_for`` one level up: workers
    adopt workers' shards, shards adopt shards' clients.  Empty when
    every shard (or none) has an alive client."""
    assignment = np.asarray(assignment)
    alive = np.asarray(alive, bool)
    covered = np.zeros(num_shards, bool)
    np.logical_or.at(covered, assignment[alive], True)
    if covered.all() or not covered.any():
        return {}
    out: dict[int, int] = {}
    for s in np.nonzero(~covered)[0]:
        a = (int(s) + 1) % num_shards
        while not covered[a]:
            a = (a + 1) % num_shards
        out[int(s)] = a
    return out


def reassign_shards(index_matrix: np.ndarray,
                    adopters: dict[int, int]) -> np.ndarray:
    """Deterministic shard reassignment for elastic membership
    (``FaultConfig.churn``): while a worker is away, its data shard is
    trained by its adopter so departed data keeps contributing.

    ``adopters`` maps departed worker -> alive adopter
    (``FaultPlan.adopters_for``).  The adopter's row for the round
    becomes the round-robin interleave of its own shard and every shard
    it adopted, truncated to the row length L — a shape-preserving
    deterministic subsample that covers all the merged shards evenly
    (L/(k+1) samples each for k adoptions).  Departed workers' own rows
    are left untouched (their lanes are frozen and never gather).
    Returns a new matrix; the input is never mutated."""
    if not adopters:
        return index_matrix
    out = index_matrix.copy()
    by_adopter: dict[int, list[int]] = {}
    for departed, adopter in sorted(adopters.items()):
        by_adopter.setdefault(adopter, []).append(departed)
    L = index_matrix.shape[1]
    for adopter, departed in by_adopter.items():
        rows = np.stack([index_matrix[adopter]]
                        + [index_matrix[i] for i in departed], axis=1)
        out[adopter] = rows.reshape(-1)[:L]
    return out
