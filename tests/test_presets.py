"""Presets: all construct valid configs; a sample runs end-to-end via CLI."""

import sys

import pytest

from dopt.presets import PRESETS, get_preset


def test_all_presets_construct():
    for name in PRESETS:
        cfg = get_preset(name)
        # exactly one engine section set per preset
        engines = [cfg.federated, cfg.gossip, cfg.seqlm]
        assert sum(e is not None for e in engines) == 1, name


def test_unknown_preset():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("nope")


def test_reference_grid_params():
    # P1 notebook cells 8/10 parameters.
    cfg = get_preset("reference-fedavg")
    assert cfg.data.num_users == 100 and cfg.seed == 2022
    assert cfg.federated.frac == 0.1 and cfg.federated.local_ep == 10
    assert cfg.optim.lr == 0.1 and cfg.model.faithful
    # P2 notebook cell 11 parameters.
    cfg = get_preset("reference-dsgd-circle")
    assert cfg.data.num_users == 6 and cfg.seed == 2028
    assert cfg.gossip.local_bs == 128 and not cfg.data.iid


def test_cli_end_to_end(devices, tmp_path, capsys):
    from dopt.run import main
    rc = main(["--preset", "baseline1", "--rounds", "2",
               "--synthetic-scale", "0.01",
               "--csv", str(tmp_path / "h.csv"),
               "--checkpoint", str(tmp_path / "ck")])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"round": 1' in out
    assert (tmp_path / "h.csv").exists()
    assert (tmp_path / "ck" / "meta.json").exists()


def test_cli_resume(devices, tmp_path, capsys):
    from dopt.run import main
    main(["--preset", "baseline1", "--rounds", "1", "--synthetic-scale", "0.01",
          "--checkpoint", str(tmp_path / "ck")])
    rc = main(["--preset", "baseline1", "--rounds", "1",
               "--synthetic-scale", "0.01", "--resume", str(tmp_path / "ck")])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"round": 1' in out  # continued from round 1


def test_cli_set_overrides(capsys):
    # baseline1 is the MLP config — conv models on the 1-core virtual
    # CPU mesh are far too slow for a CLI smoke test.
    from dopt.run import main

    rc = main(["--preset", "baseline1", "--rounds", "1",
               "--synthetic-scale", "0.05",
               "--set", "gossip.topology=complete",
               "--set", "gossip.mode=metropolis",
               "--set", "gossip.local_ep=1",
               "--set", "optim.lr=0.02",
               "--set", "seed=3"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "complete" in err and "0.02" in err


def test_cli_set_rejects_unknown_path():
    import pytest

    from dopt.run import main

    with pytest.raises(SystemExit):
        main(["--preset", "reference-dsgd-circle", "--set", "nope.lr=1"])
    with pytest.raises(SystemExit):
        main(["--preset", "reference-dsgd-circle", "--set", "badspec"])


def test_apply_override_annotation_coercion():
    import pytest

    from dopt.presets import get_preset
    from dopt.run import apply_override

    cfg = get_preset("baseline3")  # federated preset
    # None-valued optional bool coerces from the annotation, not type(None)
    c = apply_override(cfg, "federated.compact=false")
    assert c.federated.compact is False
    c = apply_override(cfg, "federated.compact=true")
    assert c.federated.compact is True
    # optional int
    c = apply_override(cfg, "mesh_devices=2")
    assert c.mesh_devices == 2
    # explicit None for optional fields
    c = apply_override(c, "mesh_devices=none")
    assert c.mesh_devices is None
    # strict bool: typos raise instead of silently meaning False
    with pytest.raises(SystemExit):
        apply_override(cfg, "data.iid=ture")
    # bad numerics raise cleanly
    with pytest.raises(SystemExit):
        apply_override(cfg, "federated.rounds=2.5")
    with pytest.raises(SystemExit):
        apply_override(cfg, "optim.lr=abc")
    # properties/methods are not fields
    with pytest.raises(SystemExit):
        apply_override(cfg, "gossip.topology=x")  # gossip is None here
    # unsupported field types are refused
    with pytest.raises(SystemExit):
        apply_override(cfg, "model.input_shape=3")


def test_apply_override_cannot_null_subtrees():
    import pytest

    from dopt.presets import get_preset
    from dopt.run import apply_override

    with pytest.raises(SystemExit):
        apply_override(get_preset("baseline1"), "gossip=none")


def test_parity_real_skips_without_data(monkeypatch, capsys):
    """The quantitative parity harness must be invocable anywhere: with
    no raw MNIST it reports an explicit skip and exits 0."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "parity_real", Path(__file__).parent.parent / "scripts" / "parity_real.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(sys, "argv", ["parity_real.py"])
    assert mod.main() == 0
    assert "skipped: no real data" in capsys.readouterr().out


def _load_replay_module():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "replay_reference",
        Path(__file__).parent.parent / "scripts" / "replay_reference.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_committed_replay_grid_orderings():
    """The committed synthetic replay grid (results/summary.json) must
    keep exhibiting the qualitative structure results/README.md claims:
    centralized ≥ complete > fedlcon > circle, star > circle, and
    {circle, star} > nocons-noniid (star vs fedlcon deliberately
    unpinned — see ORDERINGS in scripts/replay_reference.py).  A rerun
    of scripts/replay_reference.py that flips one fails here."""
    import json
    from pathlib import Path

    mod = _load_replay_module()
    summary = json.loads(
        (Path(__file__).parent.parent / "results" / "summary.json").read_text())
    assert mod.check_orderings(summary) == []


def test_replay_ordering_check_detects_flip():
    import copy
    import json
    from pathlib import Path

    mod = _load_replay_module()
    summary = json.loads(
        (Path(__file__).parent.parent / "results" / "summary.json").read_text())
    bad = copy.deepcopy(summary)
    for r in bad:
        if r["preset"] == "reference-dsgd-complete":
            r["final_acc"] = 0.01
    problems = mod.check_orderings(bad)
    assert problems and any("reference-dsgd-complete" in p for p in problems)
    # missing presets are reported, not silently passed
    assert mod.check_orderings([]) != []


def test_cli_seqlm_preset(tmp_path):
    """`--preset seqlm` drives the sequence-parallel LM engine through
    the same CLI surface as the reference engines (VERDICT r1 #8)."""
    from dopt.run import main

    csv = tmp_path / "seqlm.csv"
    rc = main(["--preset", "seqlm", "--rounds", "4",
               "--set", "seqlm.seq_len=128", "--set", "seqlm.batch=2",
               "--set", "seqlm.dim=32", "--set", "seqlm.depth=1",
               "--set", "seqlm.heads=2", "--set", "seqlm.log_every=1",
               "--csv", str(csv)])
    assert rc == 0 and csv.exists()
    text = csv.read_text()
    assert "loss" in text.splitlines()[0]
    assert len(text.splitlines()) == 5  # header + 4 logged steps
