"""Per-round vs fused-blocked bit-identity for the degraded modes.

PR 4 made every fault/robust/network mode eligible for the fused
multi-round ``lax.scan`` path by moving its round-to-round state on
device as scan carry: gossip/federated quarantine streaks (int32 carry
+ on-device matrix repair), the federated staleness one-slot buffer and
its admission schedule, push-sum mass + in-flight packet buffers (with
the per-staleness ``[D+1, n, n]`` link-matrix stacks as stacked scan
inputs), and fixed-width validity-masked compact fault lanes (survivor
counts are data, not shapes).

The contract these tests pin, per mode: ``block=1`` and ``block=k``
produce IDENTICAL History rows, fault-ledger rows (content AND order)
and final device state — plus kill-and-resume mid-block under the full
chaos cocktail.  Fast invariants run tier-1; everything that builds an
engine is ``slow`` (the tier-1 wall-clock budget is nearly full).
"""

import dataclasses

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig, RobustConfig)

pytestmark = pytest.mark.network

_DATA = DataConfig(dataset="synthetic", num_users=6, iid=True,
                   synthetic_train_size=192, synthetic_test_size=64)
_FDATA = dataclasses.replace(_DATA, num_users=8, synthetic_train_size=256)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5)


def _gossip_cfg(faults=None, robust=None, **gkw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=4, local_ep=1, local_bs=32)
    g.update(gkw)
    return ExperimentConfig(name="t", seed=7, data=_DATA, model=_MODEL,
                            optim=_OPTIM, gossip=GossipConfig(**g),
                            faults=faults, robust=robust)


def _fed_cfg(faults=None, robust=None, **fkw):
    f = dict(algorithm="fedavg", frac=1.0, rounds=4, local_ep=1,
             local_bs=32)
    f.update(fkw)
    return ExperimentConfig(name="t", seed=7, data=_FDATA, model=_MODEL,
                            optim=_OPTIM, federated=FederatedConfig(**f),
                            faults=faults, robust=robust)


def _assert_trace_equal(ta, tb, what, params=("params",)):
    """History rows, fault ledger (content and ORDER), and the named
    device-state trees must be bit-identical."""
    import jax

    assert ta.history.rows == tb.history.rows, f"{what}: history diverged"
    assert ta.history.faults == tb.history.faults, f"{what}: ledger diverged"
    for name in params:
        a = jax.device_get(getattr(ta, name))
        b = jax.device_get(getattr(tb, name))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(la, lb, err_msg=f"{what}: {name}")


# ---------------------------------------------------------------------------
# Fast invariants (tier-1)
# ---------------------------------------------------------------------------

def test_repair_for_dropout_jnp_matches_numpy():
    # The fused-quarantine path repairs the mixing matrix ON DEVICE;
    # its semantics must match the host repair exactly: dead/isolated
    # rows become exact identity rows, surviving rows stay stochastic.
    from dopt.topology import (build_mixing_matrices, repair_for_dropout,
                               repair_for_dropout_jnp)

    rng = np.random.default_rng(0)
    for topo in ("circle", "complete"):
        w = build_mixing_matrices(topo, "metropolis", 6).for_round(0)
        w32 = w.astype(np.float32)
        for _ in range(4):
            alive = (rng.random(6) > 0.4).astype(np.float32)
            host = repair_for_dropout(w32.astype(np.float64), alive)
            dev = np.asarray(repair_for_dropout_jnp(w32, alive))
            np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)
            # Dead rows are EXACT identity on both paths (no float slop
            # — a dead worker's carried state must freeze bit-exactly).
            for i in np.nonzero(alive <= 0)[0]:
                expect = np.eye(6, dtype=np.float32)[i]
                np.testing.assert_array_equal(dev[i], expect)
    # All-alive repair is exactly row-renormalisation; rows stay
    # stochastic under partial failure.
    alive = np.asarray([1, 0, 1, 1, 0, 1], np.float32)
    dev = np.asarray(repair_for_dropout_jnp(
        build_mixing_matrices("circle", "metropolis", 6)
        .for_round(0).astype(np.float32), alive))
    np.testing.assert_allclose(dev.sum(axis=1), 1.0, rtol=1e-6)


def test_sharded_eval_batches_more_workers_than_samples():
    # Satellite: workers > n used to crash on the wraparound pad-fill
    # (empty shard broadcast into a non-empty slice).  Empty shards now
    # keep zero indices at weight 0: valid gathers, zero contribution,
    # and the total weight still covers every sample exactly once.
    from dopt.data import sharded_eval_batches

    idx, wt = sharded_eval_batches(3, 5, batch_size=4)
    assert idx.shape[0] == 5 and wt.shape == idx.shape
    assert wt.sum() == 3.0                    # each sample counted once
    assert (idx >= 0).all() and (idx < 3).all()
    for i in (3, 4):                          # empty shards: weight 0
        assert wt[i].sum() == 0.0


# ---------------------------------------------------------------------------
# Per-mode per-round vs blocked bit-identity (engine runs — slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gossip_quarantine_blocked_parity(devices):
    # The newly fused mode with real detection dynamics: a persistent
    # nan liar is screened, quarantined, readmitted and reoffends —
    # with the streak/until state as scan carry on the blocked path.
    from dopt.engine import GossipTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan")
    rc = RobustConfig(clip_radius=1.0, quarantine_after=2,
                      quarantine_rounds=2)
    ta = GossipTrainer(_gossip_cfg(fc, robust=rc))
    ta.run(rounds=6, block=1)
    tb = GossipTrainer(_gossip_cfg(fc, robust=rc))
    tb.run(rounds=6, block=3)
    _assert_trace_equal(ta, tb, "gossip quarantine")
    acts = [r["action"] for r in ta.history.faults if r["worker"] == 0]
    assert any(a.startswith("quarantined_until") for a in acts), acts
    assert "readmitted" in acts


@pytest.mark.slow
def test_gossip_linkdrop_blocked_parity(devices):
    from dopt.engine import GossipTrainer

    fc = FaultConfig(msg_drop=0.3)
    ta = GossipTrainer(_gossip_cfg(fc))
    ta.run(rounds=4, block=1)
    tb = GossipTrainer(_gossip_cfg(fc))
    tb.run(rounds=4, block=4)
    _assert_trace_equal(ta, tb, "link drop")
    assert any(r["kind"] == "msg_drop" for r in ta.history.faults)


@pytest.mark.slow
def test_gossip_pushsum_blocked_parity(devices):
    # Push-sum mass and the in-flight packet buffers are scan carry;
    # the [D+1, n, n] per-staleness stacks are stacked scan inputs.
    # Mass + buffers must come out of the fused block bit-identical.
    import jax

    from dopt.engine import GossipTrainer

    fc = FaultConfig(msg_drop=0.2, msg_delay=0.3, msg_delay_max=2)
    ta = GossipTrainer(_gossip_cfg(fc, correction="push_sum"))
    ta.run(rounds=5, block=1)
    tb = GossipTrainer(_gossip_cfg(fc, correction="push_sum"))
    tb.run(rounds=5, block=3)
    _assert_trace_equal(ta, tb, "push-sum")
    np.testing.assert_array_equal(np.asarray(ta._mass),
                                  np.asarray(tb._mass))
    for la, lb in zip(jax.tree.leaves(jax.device_get(ta._link_buf)),
                      jax.tree.leaves(jax.device_get(tb._link_buf))):
        np.testing.assert_array_equal(la, lb)
    np.testing.assert_array_equal(np.asarray(ta._link_buf_mass),
                                  np.asarray(tb._link_buf_mass))


@pytest.mark.slow
def test_federated_staleness_blocked_parity(devices):
    # Deadline-missed stragglers and delayed uplinks are captured into
    # the one-slot device buffer and admitted <= K rounds late at decay
    # weight — capture/admission now decided ON DEVICE inside the scan.
    import jax

    from dopt.engine import FederatedTrainer

    fc = FaultConfig(straggle=0.6, straggle_frac=0.5,
                     straggler_policy="drop", over_select=0.3,
                     msg_drop=0.1, msg_delay=0.2, msg_delay_max=2)
    ta = FederatedTrainer(_fed_cfg(fc, frac=0.5, staleness_max=2,
                                   staleness_decay=0.7))
    ta.run(rounds=6, block=1)
    tb = FederatedTrainer(_fed_cfg(fc, frac=0.5, staleness_max=2,
                                   staleness_decay=0.7))
    tb.run(rounds=6, block=3)
    _assert_trace_equal(ta, tb, "staleness", params=("theta", "params"))
    for la, lb in zip(jax.tree.leaves(jax.device_get(ta._stale_p)),
                      jax.tree.leaves(jax.device_get(tb._stale_p))):
        np.testing.assert_array_equal(la, lb)
    assert any(r["kind"] == "staleness" for r in ta.history.faults)


@pytest.mark.slow
def test_federated_quarantine_blocked_parity(devices):
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan")
    rc = RobustConfig(quarantine_after=2, quarantine_rounds=2)
    ta = FederatedTrainer(_fed_cfg(fc, robust=rc))
    ta.run(rounds=8, block=1)
    tb = FederatedTrainer(_fed_cfg(fc, robust=rc))
    tb.run(rounds=8, block=4)
    _assert_trace_equal(ta, tb, "fed quarantine", params=("theta", "params"))
    acts = [r["action"] for r in ta.history.faults if r["worker"] == 0]
    assert any(a.startswith("quarantined_until") for a in acts), acts


@pytest.mark.slow
def test_federated_stale_plus_quarantine_blocked_parity(devices):
    # The composition case: buffered late updates from a worker that
    # gets quarantined mid-flight are dropped on admission; both the
    # admission schedule AND the quarantine state ride the same carry.
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(straggle=0.5, straggle_frac=0.5,
                     straggler_policy="drop", corrupt=0.4,
                     corrupt_mode="nan", msg_delay=0.2, msg_delay_max=2)
    rc = RobustConfig(quarantine_after=2, quarantine_rounds=3)
    ta = FederatedTrainer(_fed_cfg(fc, frac=0.5, staleness_max=2,
                                   robust=rc))
    ta.run(rounds=8, block=1)
    tb = FederatedTrainer(_fed_cfg(fc, frac=0.5, staleness_max=2,
                                   robust=rc))
    tb.run(rounds=8, block=4)
    _assert_trace_equal(ta, tb, "stale+quar", params=("theta", "params"))


@pytest.mark.slow
def test_compact_faults_fixed_width_blocked_parity(devices):
    # Compact + faults: survivor counts are DATA (validity-masked
    # fixed-width lanes), so faulted compact rounds share one compiled
    # program and fuse into blocks.  Full-width stays the semantic
    # reference: identical ledger, metrics equal to tolerance (the
    # sampled mean sums lanes in a different order).
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(crash=0.2, straggle=0.3, straggle_frac=0.5,
                     corrupt=0.3, corrupt_mode="signflip")
    ca = dataclasses.replace(_fed_cfg(fc, frac=0.5, compact=True),
                             mesh_devices=1)
    ta = FederatedTrainer(ca)
    ta.run(rounds=5, block=1)
    tb = FederatedTrainer(dataclasses.replace(ca))
    tb.run(rounds=5, block=5)
    _assert_trace_equal(ta, tb, "compact faults",
                        params=("theta", "params"))
    tf = FederatedTrainer(dataclasses.replace(
        _fed_cfg(fc, frac=0.5, compact=False), mesh_devices=1))
    tf.run(rounds=5)
    assert tf.history.faults == tb.history.faults
    for rc_, rf_ in zip(tb.history.rows, tf.history.rows):
        for k in rc_:
            np.testing.assert_allclose(rc_[k], rf_[k], rtol=2e-4,
                                       atol=2e-5)


@pytest.mark.slow
def test_gossip_cocktail_blocked_parity(devices):
    # The bench.py chaos cocktail: msg_drop + straggle + corrupt(scale)
    # + quarantine armed, through the link consensus path (quarantine
    # composes via the alive machinery).
    from dopt.engine import GossipTrainer

    fc = FaultConfig(msg_drop=0.1, straggle=0.3, straggle_frac=0.5,
                     corrupt=0.2, corrupt_mode="scale", corrupt_scale=5.0)
    rc = RobustConfig(quarantine_after=2, quarantine_rounds=3)
    ta = GossipTrainer(_gossip_cfg(fc, robust=rc))
    ta.run(rounds=4, block=1)
    tb = GossipTrainer(_gossip_cfg(fc, robust=rc))
    tb.run(rounds=4, block=4)
    _assert_trace_equal(ta, tb, "gossip cocktail")


@pytest.mark.parametrize("engine", [
    pytest.param("gossip", marks=pytest.mark.slow),
    pytest.param("federated", marks=pytest.mark.slow),
])
def test_cocktail_kill_and_resume_mid_block(engine, tmp_path, devices):
    # Blocked chaos execution checkpoints at block boundaries; a run
    # killed there and resumed (still blocked) must be bit-identical to
    # the continuous blocked run — carry state (quarantine streaks,
    # staleness schedule, buffers, push-sum mass) reloads exactly.
    from dopt.engine import FederatedTrainer, GossipTrainer

    if engine == "gossip":
        fc = FaultConfig(msg_drop=0.15, msg_delay=0.2, msg_delay_max=2,
                         straggle=0.3, straggle_frac=0.5,
                         corrupt=0.2, corrupt_mode="scale",
                         corrupt_scale=5.0)
        rc = RobustConfig(quarantine_after=2, quarantine_rounds=3)

        def make():
            return GossipTrainer(_gossip_cfg(fc, robust=rc,
                                             correction="push_sum"))
    else:
        fc = FaultConfig(straggle=0.5, straggle_frac=0.5,
                         straggler_policy="drop", corrupt=0.4,
                         corrupt_mode="nan", msg_delay=0.2,
                         msg_delay_max=2)
        rc = RobustConfig(quarantine_after=2, quarantine_rounds=3)

        def make():
            return FederatedTrainer(_fed_cfg(fc, frac=0.5,
                                             staleness_max=2, robust=rc))

    cont = make()
    hc = cont.run(rounds=8, block=2)
    path = tmp_path / f"{engine}-ckpt"
    part = make()
    part.run(rounds=4, block=2, checkpoint_every=2, checkpoint_path=path)
    res = make()
    res.restore(path)
    assert res.round == 4
    hr = res.run(rounds=4, block=2)
    assert hr.rows == hc.rows
    assert hr.faults == hc.faults
