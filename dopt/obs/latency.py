"""SLO latency channel: fixed-bucket histograms over ``latency`` events.

The serve daemon measures the runtime latencies the ROADMAP's phase-2
soak item names outright — checkpoint-restore p99, alert latency under
live churn — and streams each observation as a non-deterministic v1
``latency`` event (``dopt.obs.events``).  This module is the math under
them: a stdlib fixed-bucket histogram with JSON-able state (like the
rule windows, so a monitor checkpoint carries it across restarts),
quantile estimation, and the Prometheus *histogram* exposition
(``_bucket``/``_sum``/``_count`` with cumulative ``le`` labels) that
``PrometheusSink`` renders.

The SLO latency names a served run records (``SLO_LATENCIES``):

``boundary_tick``       one round-boundary visit of the serve
                        controller — command ingest, directive
                        publish/await, apply, checkpoint decision —
                        the per-round control-plane overhead;
``command_apply``       enqueue → applied: the queue ``ts`` the
                        submitter stamped to the boundary that applied
                        the command (what an operator actually waits);
``checkpoint_save``     one atomic checkpoint (fleet barrier included);
``checkpoint_restore``  one restore — daemon start resume or a
                        config-change rebuild's restore leg;
``alert_latency``       the triggering round bundle's ``ts`` to the
                        alert event's ``ts`` — how stale a page is by
                        the time it exists.

Buckets are fixed (``DEFAULT_BUCKETS``: 1 ms → 120 s, log-spaced, +Inf
overflow) so histograms merge across processes and restarts by adding
counts; quantiles interpolate linearly inside the owning bucket and
clamp to the observed min/max, so small samples report honest values
instead of bucket-edge artifacts.

Stdlib-only (no jax/numpy): the aggregator and the soak's SLO report
run anywhere the checker does.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

# The latency names a served run records; the soak's SLO report asserts
# finite p50/p99 for each (alert_latency only when an alert fired).
SLO_LATENCIES = ("boundary_tick", "command_apply", "checkpoint_save",
                 "checkpoint_restore", "alert_latency")

# Fixed upper bounds in seconds (the +Inf overflow bucket is implicit):
# 1 ms resolution at the fast end (an idle boundary tick), 120 s at the
# slow end (a fleet checkpoint barrier on a loaded host).  Fixed, not
# adaptive: histograms with identical bounds merge by adding counts.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

QUANTILES = (0.5, 0.95, 0.99)


class LatencyHistogram:
    """Fixed-bucket latency histogram with JSON-able state.

    ``counts[i]`` holds observations in ``(bounds[i-1], bounds[i]]``
    (first bucket from 0); ``counts[-1]`` is the +Inf overflow.  State
    round-trips through JSON exactly (ints and the float bounds), so it
    checkpoints like a rule window.
    """

    def __init__(self, bounds: Iterable[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly "
                             f"increasing, got {self.bounds}")
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def observe(self, seconds: float) -> None:
        v = float(seconds)
        if not math.isfinite(v) or v < 0:
            raise ValueError(f"latency observation must be finite "
                             f">= 0, got {seconds!r}")
        i = 0
        while i < len(self.bounds) and v > self.bounds[i]:
            i += 1
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> float | None:
        """Estimate the ``q``-quantile (0 < q <= 1) by linear
        interpolation inside the owning bucket, clamped to the observed
        [min, max]; None when empty."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = (self.bounds[i] if i < len(self.bounds)
                      else (self.max if self.max is not None else lo))
                frac = (target - cum) / c
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                lo_clamp = self.min if self.min is not None else est
                hi_clamp = self.max if self.max is not None else est
                return max(lo_clamp, min(hi_clamp, est))
            cum += c
        return self.max

    def summary(self) -> dict[str, Any]:
        """The p50/p95/p99 block the HealthReport and the soak's SLO
        report carry."""
        out: dict[str, Any] = {"count": self.count,
                               "sum": round(self.sum, 6),
                               "min": self.min, "max": self.max}
        for q in QUANTILES:
            v = self.quantile(q)
            out[f"p{int(q * 100)}"] = None if v is None else round(v, 6)
        return out

    # -- state (JSON round-trip, like rule windows) --------------------
    def state(self) -> dict[str, Any]:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}

    @classmethod
    def from_state(cls, st: dict[str, Any]) -> "LatencyHistogram":
        h = cls(st.get("bounds", DEFAULT_BUCKETS))
        counts = list(st.get("counts", []))
        if len(counts) == len(h.counts):
            h.counts = [int(c) for c in counts]
        h.count = int(st.get("count", sum(h.counts)))
        h.sum = float(st.get("sum", 0.0))
        h.min = st.get("min")
        h.max = st.get("max")
        return h

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Add ``other``'s counts into this histogram (fixed identical
        bounds are the contract that makes cross-process merges exact)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket bounds")
        self.counts = [a + b for a, b in zip(self.counts, other.counts)]
        self.count += other.count
        self.sum += other.sum
        for v in (other.min, other.max):
            if v is None:
                continue
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
        return self

    # -- Prometheus histogram exposition -------------------------------
    def exposition(self, family: str, labels: str = "") -> list[str]:
        """The ``_bucket``/``_sum``/``_count`` sample lines for this
        histogram (cumulative ``le`` per the exposition format).
        ``labels`` is a pre-rendered ``name="value"`` fragment the
        ``le`` label is appended to."""
        sep = "," if labels else ""
        lines = []
        cum = 0
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            lines.append(f'{family}_bucket{{{labels}{sep}le="{bound:g}"}} '
                         f"{cum}")
        lines.append(f'{family}_bucket{{{labels}{sep}le="+Inf"}} '
                     f"{self.count}")
        brace = f"{{{labels}}}" if labels else ""
        lines.append(f"{family}_sum{brace} {self.sum!r}")
        lines.append(f"{family}_count{brace} {self.count}")
        return lines


def summarize_latency_events(events: Iterable[dict]) -> dict[str, Any]:
    """Fold a stream's ``latency`` events into per-name summaries —
    the soak's SLO report in one call (events from several processes'
    merged streams simply add up; the buckets are fixed)."""
    hists: dict[str, LatencyHistogram] = {}
    for ev in events:
        if ev.get("kind") != "latency":
            continue
        name = str(ev.get("name"))
        v = ev.get("seconds")
        if isinstance(v, (int, float)) and math.isfinite(v) and v >= 0:
            hists.setdefault(name, LatencyHistogram()).observe(float(v))
    return {name: h.summary() for name, h in sorted(hists.items())}
