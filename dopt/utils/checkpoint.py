"""Checkpoint / resume (absent in the reference — SURVEY §5).

The reference persists only metric CSVs; model state lives and dies
with the Colab runtime (the only continuity is ``Server.global_round``
surviving across ``run()`` calls in memory, servers.py:18,78).  dopt
checkpoints the full training state — stacked params, momentum buffers,
ADMM duals, global model, round counter, and metric history — with
orbax for the array pytrees plus a JSON sidecar for scalars/history.

Layout:  <dir>/state/   orbax pytree checkpoint
         <dir>/meta.json  {round, name, history rows, fault ledger,
                           quarantine/staleness host mirrors, and — for
                           population runs (dopt.population) — the
                           client registry under 'population_registry'
                           (participation counts, client-keyed streaks
                           and sentences, shard-assignment integrity
                           vector; the cohort sampler is stateless, so
                           no RNG state rides along)}

Saves are atomic: the new checkpoint is fully materialised in a
``<dir>.tmp`` sibling, the previous checkpoint (if any) is parked at
``<dir>.old``, and only then is the sibling renamed into place.  A crash
at any point leaves at least one complete checkpoint loadable —
``load_checkpoint`` transparently falls back to ``<dir>.old`` when the
primary directory is missing or incomplete.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False


def _to_numpy(tree):
    """Materialise a pytree on the host.  On a multi-process fleet
    (``dopt serve`` on real ``jax.distributed`` process groups) the
    worker-stacked state is sharded ACROSS processes — a bare
    ``device_get`` of a non-fully-addressable array raises — so those
    leaves ride a ``process_allgather`` instead.  The allgather is a
    COLLECTIVE: every process of the fleet must reach the checkpoint
    together (the serve barrier protocol guarantees it); followers then
    pass ``write=False`` to ``save_checkpoint`` and only the leader
    touches the filesystem.  Single-process arrays are always fully
    addressable, so scripted runs take the exact pre-change path."""
    def _np(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(
                x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(_np, tree)


def _write_state(dest: Path, arrays: dict[str, Any]) -> None:
    """Materialise the arrays pytree under ``dest`` (orbax or npz).

    On a multi-process fleet the npz path is used even with orbax
    installed: ``PyTreeCheckpointer.save`` runs its own cross-process
    barrier, but serve fleets have exactly ONE writer (followers
    already joined the allgather and skip the filesystem), so the
    orbax barrier would wait forever for processes that were never
    going to save.  The arrays are plain host numpy by this point —
    npz loses nothing."""
    if HAVE_ORBAX and jax.process_count() <= 1:
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(dest / "state", arrays)
    else:  # numpy path: no orbax, or a single-writer multi-process fleet
        np.savez(dest / "state.npz", **_flatten_for_npz(arrays))


def _write_meta(dest: Path, meta: dict[str, Any]) -> None:
    (dest / "meta.json").write_text(json.dumps(meta, indent=2))


# Completeness marker: written LAST into the staging dir, it records
# every checkpoint file's size.  ``_is_complete`` cross-checks the
# manifest against the files on disk, so a checkpoint truncated by a
# mid-write crash (or a partial copy) is detected and rejected instead
# of loaded as garbage.
_MARKER = "complete.json"


def _write_marker(dest: Path) -> None:
    files = {
        str(p.relative_to(dest)): p.stat().st_size
        for p in sorted(dest.rglob("*"))
        if p.is_file() and p.name != _MARKER
    }
    (dest / _MARKER).write_text(json.dumps(files, indent=2))


def save_checkpoint(path: str | Path, *, arrays: dict[str, Any],
                    meta: dict[str, Any], write: bool = True) -> Path:
    """Save an arrays pytree (orbax) + JSON metadata, atomically.

    The previous checkpoint at ``path`` is never modified in place: the
    new one is built in ``<path>.tmp`` and swapped in via two renames
    (old → ``<path>.old``, tmp → ``path``).  A crash anywhere in between
    leaves either ``path`` or ``<path>.old`` as a complete checkpoint.

    ``write=False`` (multi-process serve followers) still runs the
    host materialisation — whose cross-process allgather is a
    collective every process must join — but skips the filesystem
    entirely: one fleet, one writer, no rename races.
    """
    path = Path(path).absolute()
    arrays = {k: _to_numpy(v) for k, v in arrays.items() if v is not None}
    if not write:
        return path
    path.parent.mkdir(parents=True, exist_ok=True)

    tmp = path.with_name(path.name + ".tmp")
    old = path.with_name(path.name + ".old")
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    _write_state(tmp, arrays)
    _write_meta(tmp, meta)
    _write_marker(tmp)

    # Swap: park the previous checkpoint, promote the new one, then drop
    # the parked copy.  os.replace cannot overwrite a non-empty dir, so
    # the parked copy doubles as the crash-window fallback.  When the
    # primary is MISSING (we are saving after a crash that left only
    # ``<path>.old``), the parked copy is the sole good checkpoint — it
    # must survive until the promotion rename lands, so the cleanup
    # happens strictly after ``os.replace(tmp, path)`` in every case.
    if path.exists():
        if old.exists():
            shutil.rmtree(old)   # safe: primary still intact
        os.replace(path, old)
    os.replace(tmp, path)
    if old.exists():
        shutil.rmtree(old)
    return path


def _is_complete(path: Path) -> bool:
    if not (path / "meta.json").exists():
        return False
    if not ((path / "state").exists() or (path / "state.npz").exists()):
        return False
    marker = path / _MARKER
    if not marker.exists():
        # Pre-manifest checkpoint: only the presence check is possible.
        return True
    try:
        manifest = json.loads(marker.read_text())
    except ValueError:
        return False
    for rel, size in manifest.items():
        f = path / rel
        if not f.is_file() or f.stat().st_size != int(size):
            return False
    return True


class IncompleteCheckpointError(RuntimeError):
    """Neither the checkpoint nor its ``.old`` fallback is complete
    (mid-write crash, truncation, or partial copy)."""


def load_checkpoint(path: str | Path) -> tuple[dict[str, Any], dict[str, Any]]:
    """Returns (arrays, meta).

    Falls back to ``<path>.old`` when ``path`` is absent or incomplete
    (the save crashed between the two promotion renames).
    """
    path = Path(path).absolute()
    if not _is_complete(path):
        old = path.with_name(path.name + ".old")
        if _is_complete(old):
            path = old
        else:
            raise IncompleteCheckpointError(
                f"checkpoint at {path} is missing, truncated, or "
                "incomplete (its size manifest does not match the files "
                f"on disk), and no complete fallback exists at {old}; "
                "re-save from a live trainer or point at an earlier "
                "checkpoint")
    meta = json.loads((path / "meta.json").read_text())
    if HAVE_ORBAX and (path / "state").exists():
        ckpt = ocp.PyTreeCheckpointer()
        arrays = ckpt.restore(path / "state")
    else:
        with np.load(path / "state.npz") as z:
            arrays = _unflatten_from_npz(dict(z))
    return arrays, meta


def meta_expect(meta: dict[str, Any], *, what: str = "checkpoint",
                **expected: Any) -> None:
    """Validate checkpoint metadata fields against expected values.

    Collects EVERY mismatching (or absent-but-expected) field into one
    ValueError instead of failing on the first, so a wrong-config
    resume reports the whole disagreement at once.  Fields the
    checkpoint predates (absent AND expected None) pass — older
    checkpoints stay loadable."""
    problems = []
    for key, want in expected.items():
        got = meta.get(key)
        if got is None and want is None:
            continue
        if got != want:
            problems.append(f"{key}={got!r} (trainer expects {want!r})")
    if problems:
        raise ValueError(
            f"{what} does not match this trainer: " + "; ".join(problems))


def _flatten_for_npz(tree, prefix="") -> dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten_for_npz(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten_from_npz(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out
