"""Five-config benchmark suite: TPU throughput + speedup vs the
sequential torch-CPU oracle on every BASELINE.json config.

For each preset (baseline1..baseline5):
  * TPU side — the preset's workload in throughput trim (bfloat16
    compute, native C++ batch planner, fused round blocks for gossip),
    compiled once, then a timed steady-state window → rounds/sec and
    samples/sec.  Numerics/accuracy parity is covered separately by the
    oracle-parity tests and the reference replay grid
    (scripts/replay_reference.py); this suite measures speed.
  * Oracle side — the reference's execution model: N workers stepped
    SEQUENTIALLY in one process with torch SGD (SURVEY §2: the
    reference simulates distribution by looping over clients).  We time
    ONE worker's local round on the same batch plan and extrapolate
    ×(workers stepped per round) — sequential cost is linear by
    construction, and the extrapolation ignores consensus/eval cost,
    which only makes the oracle FASTER (speedups reported are lower
    bounds).

Writes results to --out (default results/bench_suite.json) and prints
one summary line per config.  Run on an otherwise-idle machine: the
oracle numbers are host-CPU timings and concurrent load inflates them
(which would overstate the reported speedups).

Usage: python scripts/bench_suite.py [--quick] [--only baseline2 ...]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


# ---------------------------------------------------------------------
# Torch counterpart models (CPU oracle timing)
# ---------------------------------------------------------------------

def _torch_model(model_cfg, input_shape):
    """A torch module matching the dopt zoo model's architecture closely
    enough for fair CPU step timing (same layer shapes and FLOPs)."""
    import torch.nn as nn

    name = model_cfg.model
    if name in ("model1", "model3"):
        from dopt.engine.oracle import torch_reference_cnn

        in_ch = input_shape[-1]
        spatial = input_shape[0]
        hidden = 512 if name == "model1" else 256
        return torch_reference_cnn(in_ch, spatial, hidden,
                                   num_classes=model_cfg.num_classes,
                                   faithful=model_cfg.faithful)
    if name == "mlp":
        flat = int(np.prod(input_shape))
        return nn.Sequential(
            nn.Flatten(), nn.Linear(flat, 200), nn.ReLU(),
            nn.Linear(200, 200), nn.ReLU(),
            nn.Linear(200, model_cfg.num_classes),
        )
    if name == "logistic":
        flat = int(np.prod(input_shape))
        return nn.Sequential(nn.Flatten(),
                             nn.Linear(flat, model_cfg.num_classes))
    if name == "resnet18":
        return _torch_resnet18(in_ch=input_shape[-1],
                               num_classes=model_cfg.num_classes)
    raise ValueError(f"no torch counterpart for model {name!r}")


def _torch_resnet18(in_ch: int = 3, num_classes: int = 10):
    """CIFAR-style ResNet-18 with GroupNorm — the torch twin of
    dopt.models.zoo.ResNet18 (same stage layout and widths)."""
    import torch.nn as nn

    def gn(c):
        return nn.GroupNorm(min(32, c), c)

    class Block(nn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.n1 = gn(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.n2 = gn(cout)
            self.relu = nn.ReLU()
            if stride != 1 or cin != cout:
                self.short = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride, bias=False), gn(cout))
            else:
                self.short = nn.Identity()

        def forward(self, x):
            y = self.relu(self.n1(self.conv1(x)))
            y = self.n2(self.conv2(y))
            return self.relu(y + self.short(x))

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.stem = nn.Sequential(
                nn.Conv2d(in_ch, 64, 3, 1, 1, bias=False), gn(64), nn.ReLU())
            layers = []
            cin = 64
            for stage, blocks in enumerate((2, 2, 2, 2)):
                cout = 64 * (2 ** stage)
                for b in range(blocks):
                    layers.append(Block(cin, cout,
                                        2 if (stage > 0 and b == 0) else 1))
                    cin = cout
            self.body = nn.Sequential(*layers)
            self.head = nn.Linear(512, num_classes)

        def forward(self, x):
            x = self.body(self.stem(x))
            return self.head(x.mean(dim=(2, 3)))

    return Net()


def oracle_round_seconds(cfg, index_matrix, dataset, *, local_ep, local_bs,
                         workers_per_round,
                         max_steps=None) -> tuple[float, int, int]:
    """Time ONE worker's local round with torch on CPU and extrapolate to
    the sequential cost of all ``workers_per_round`` workers.  Returns
    (seconds, steps actually timed, steps per worker round)."""
    from dopt.data import make_batch_plan
    from dopt.engine.oracle import OracleWorker

    model = _torch_model(cfg.model, cfg.model.input_shape)
    worker = OracleWorker(model, lr=cfg.optim.lr, momentum=cfg.optim.momentum)
    plan = make_batch_plan(index_matrix, batch_size=local_bs,
                           local_ep=local_ep, seed=cfg.seed, round_idx=0,
                           workers=np.array([0]))
    idx, weight = plan.idx[0], plan.weight[0]
    steps_timed = idx.shape[0]
    if max_steps is not None and idx.shape[0] > max_steps:
        idx, weight = idx[:max_steps], weight[:max_steps]
        steps_timed = max_steps
    bx = dataset.train_x[idx]
    if bx.ndim == 5:  # [S,B,H,W,C] image batches -> torch [S,B,C,H,W]
        bx = np.ascontiguousarray(np.transpose(bx, (0, 1, 4, 2, 3)))
    by = dataset.train_y[idx]
    steps_total = plan.idx.shape[1]

    # Warm up the TRAINING path (autograd graph construction, SGD
    # momentum-buffer allocation) so the timed window measures
    # steady-state steps — otherwise per_step is biased high and the
    # "speedups are lower bounds" guarantee breaks.
    worker.local_update(bx[:1], by[:1], weight[:1])
    t0 = time.perf_counter()
    worker.local_update(bx, by, weight)
    elapsed = time.perf_counter() - t0
    per_step = elapsed / idx.shape[0]
    return per_step * steps_total * workers_per_round, steps_timed, steps_total


# ---------------------------------------------------------------------
# TPU measurement
# ---------------------------------------------------------------------

def measure_preset(name: str, *, quick: bool, skip_oracle: bool) -> dict:
    from dopt.engine import FederatedTrainer, GossipTrainer
    from dopt.presets import get_preset

    cfg = get_preset(name)
    # Throughput trim: bf16 compute + native host planner.  Same
    # algorithm, topology, data partition, and round structure.
    from dopt.presets import TRIM_COMPUTE_DTYPE

    cfg = cfg.replace(
        model=dataclasses.replace(
            cfg.model,
            compute_dtype=TRIM_COMPUTE_DTYPE.get(name, "bfloat16")),
        data=dataclasses.replace(cfg.data, plan_impl="native"),
    )
    if cfg.gossip is not None:
        # Sharded per-round eval (see GossipConfig.eval_mode): the
        # measured window carries the per-round metric without paying
        # W·|test| sample-forwards for it.
        cfg = cfg.replace(gossip=dataclasses.replace(
            cfg.gossip, eval_mode="sharded"))
    is_gossip = cfg.gossip is not None
    g = cfg.gossip if is_gossip else cfg.federated
    # Tiny models (baseline4's 248-param logistic) get a long fused
    # window: per-scan-iteration overhead is the whole round there, so
    # a short window would time the dispatch floor's variance, not the
    # workload.
    tiny = cfg.model.model == "logistic"
    rounds = 3 if quick else (5 if cfg.model.model == "resnet18"
                              else 200 if tiny else 10)

    trainer = (GossipTrainer if is_gossip else FederatedTrainer)(cfg)
    run_kwargs = {"block": rounds}
    trainer.run(rounds=rounds, **run_kwargs)           # compile + warmup
    from dopt.utils.profiling import PhaseTimers

    trainer.timers = PhaseTimers()   # phase breakdown = measured window only
    t0 = time.perf_counter()
    trainer.run(rounds=rounds, **run_kwargs)
    elapsed = time.perf_counter() - t0
    rps = rounds / elapsed

    w = cfg.data.num_users
    part_len = trainer.index_matrix.shape[1]
    if is_gossip:
        workers_per_round = w
    else:
        workers_per_round = max(int(cfg.federated.frac * w), 1)
    samples_per_round = workers_per_round * g.local_ep * part_len
    sps = rps * samples_per_round

    # MFU accounting for EVERY config (same meter as bench.py's
    # headline): training FLOPs/sample from XLA's compiled cost
    # analysis of the zoo model — generic, no per-model tables.
    import jax

    from dopt.utils.profiling import device_peak_flops, train_flops_per_sample

    p0 = jax.tree.map(lambda x: np.asarray(x[0]),
                      jax.device_get(trainer.params))
    tfps = train_flops_per_sample(
        lambda p, x: trainer.model.apply({"params": p}, x), p0,
        cfg.model.input_shape)
    if tfps != tfps:  # NaN: backend returned no cost analysis — keep the
        peak = None   # throughput numbers, drop the FLOP-derived fields.
        flops_per_round = float("nan")
        kind, _ = device_peak_flops()
    else:
        flops_per_round = tfps * samples_per_round
        kind, peak = device_peak_flops()

    out = {
        "preset": name,
        "model": cfg.model.model,
        "params": trainer.param_count,
        "workers": w,
        "workers_per_round": workers_per_round,
        "local_ep": g.local_ep,
        "local_bs": g.local_bs,
        "rounds_measured": rounds,
        "block_rounds_used": rounds,   # all measured rounds fused in ONE
        # lax.scan jit dispatch (the dispatch-overhead killer for small
        # models — baseline4's 248-param logistic round is pure host
        # overhead without it)
        "tpu_rounds_per_sec": round(rps, 4),
        "tpu_samples_per_sec": round(sps, 1),
        "device_kind": kind,
        "compute_dtype": cfg.model.compute_dtype,
        # Measured-window phase attribution (PhaseTimers): round_step is
        # the blocking device time of the fused scan dispatch,
        # host_batch_plan the host-side planning.
        "phases": trainer.timers.summary(),
    }
    if tfps == tfps:  # not NaN
        out["train_flops_per_sample"] = round(tfps)
        out["flops_per_round"] = round(flops_per_round)
        out["model_tflops_per_sec"] = round(sps * tfps / 1e12, 3)
    if peak:
        out["mfu_vs_bf16_peak"] = round(sps * tfps / peak, 4)
    if not skip_oracle:
        # resnet18: a full 800-step round on 1 CPU core takes ~minutes;
        # 24 timed steady-state steps bound the per-step time well (the
        # extrapolation provenance is recorded in oracle_steps_timed).
        max_steps = 8 if quick else (24 if cfg.model.model == "resnet18"
                                     else None)
        oracle_s, steps_timed, steps_total = oracle_round_seconds(
            cfg, trainer.index_matrix, trainer.dataset,
            local_ep=g.local_ep, local_bs=g.local_bs,
            workers_per_round=workers_per_round, max_steps=max_steps)
        out["oracle_round_sec_extrapolated"] = round(oracle_s, 3)
        out["oracle_rounds_per_sec"] = round(1.0 / oracle_s, 5)
        # Provenance of the extrapolation: per-step time measured over
        # steps_timed of the round's steps_total steps, one worker,
        # then scaled linearly (sequential execution is linear).
        out["oracle_steps_timed"] = steps_timed
        out["oracle_steps_per_worker_round"] = steps_total
        out["speedup_vs_sequential_torch_cpu"] = round(oracle_s * rps, 1)
        # Is the ≥50× north-star bar a COMPUTE comparison for this
        # config?  Decided from utilisation, independently of whether
        # the speedup happened to reach 50: when the round runs below
        # 1% of the chip's peak (mfu), >99% of its wall-clock is
        # dispatch/latency overhead — the measured 1/rps is then the
        # framework's per-round latency FLOOR, not a compute time, and
        # any speedup ratio against it grades latency, not the compute
        # path.  At that floor, hitting 50× would need
        #   flops_per_round ≥ 50 × (1/rps) × oracle_flops_per_sec,
        # which is reported so the gap is quantified, not hand-waved.
        oracle_fps = flops_per_round / oracle_s
        if oracle_fps == oracle_fps:  # not NaN (cost analysis available)
            out["oracle_flops_per_sec"] = round(oracle_fps)
        if peak:
            latency_bound = (sps * tfps / peak) < 0.01
            out["speedup_is_compute_comparison"] = not latency_bound
            if latency_bound:
                min_flops_50x = 50.0 * (1.0 / rps) * oracle_fps
                out["min_flops_per_round_for_50x_at_this_floor"] = round(
                    min_flops_50x)
                out["note"] = (
                    "TPU round is latency-floor-bound, not compute-bound "
                    f"(mfu {sps * tfps / peak:.2e} < 1% of bf16 peak): at "
                    f"the {1e3 / rps:.2f} ms/round floor the "
                    "50x-vs-sequential-CPU bar needs >= "
                    f"{min_flops_50x:.3g} FLOP/round, this config has "
                    f"{flops_per_round:.3g} — the speedup column here "
                    "measures dispatch latency, not the compute path.")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewer rounds / truncated oracle (CI-ish)")
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--skip-oracle", action="store_true")
    ap.add_argument("--out", default="results/bench_suite.json")
    args = ap.parse_args()

    names = args.only or ["baseline1", "baseline2", "baseline3",
                          "baseline4", "baseline5"]
    results = []
    for name in names:
        r = measure_preset(name, quick=args.quick,
                           skip_oracle=args.skip_oracle)
        results.append(r)
        speed = r.get("speedup_vs_sequential_torch_cpu")
        print(f"{name}: {r['tpu_rounds_per_sec']} rounds/s "
              f"({r['tpu_samples_per_sec']:.0f} samples/s, "
              f"{r['workers']} workers, {r['params']:,} params)"
              + (f" — {speed}x vs sequential torch-CPU" if speed else ""))

    import jax

    out = Path(args.out)
    if args.only and out.exists():
        # Partial regeneration: replace only the re-run presets, keep
        # the rest (their oracle columns are expensive to recompute).
        old_rows = json.loads(out.read_text())["results"]
        fresh = {r["preset"]: r for r in results}
        results = [fresh.pop(r["preset"], r) for r in old_rows]
        results += list(fresh.values())
    payload = {
        "suite": "dopt bench_suite",
        "device": str(jax.devices()[0]),
        "quick": args.quick,
        "results": results,
    }
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
