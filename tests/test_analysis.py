"""The static gates gate themselves: fixture snippets pin each lint
rule's accept/reject behaviour, the eligibility extractor round-trips a
synthetic module and must stay in sync with the committed artifacts on
the real tree, and the fingerprint sabotage test proves the off-path
gate catches a default-path program change (and stays green on an
unchanged tree)."""

from __future__ import annotations

import dataclasses
import json
import textwrap
from pathlib import Path

import pytest

from dopt.analysis.common import (EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE,
                                  parse_pragmas)
from dopt.analysis.eligibility import (cross_check, doc_key, harvest,
                                       parse_doc_rows, render_doc_table,
                                       site_key)
from dopt.analysis.lint import lint_source

REPO = Path(__file__).resolve().parent.parent


def _rules(findings):
    return sorted(f.rule for f in findings)


def _lint(snippet: str, path: str = "dopt/somelib.py"):
    return lint_source(textwrap.dedent(snippet), path)


# ---------------------------------------------------------------------
# lint: wallclock
# ---------------------------------------------------------------------

def test_wallclock_flagged():
    f = _lint("""
        import time
        def f():
            return time.time()
    """)
    assert _rules(f) == ["wallclock"]


def test_wallclock_from_import_and_datetime():
    f = _lint("""
        from time import perf_counter
        import datetime
        def f():
            return perf_counter() + datetime.datetime.now().year
    """)
    assert _rules(f) == ["wallclock", "wallclock"]


def test_wallclock_pragma_with_justification_suppresses():
    f = _lint("""
        import time
        def f():
            return time.time()  # dopt: allow-wallclock -- span timing
    """)
    assert f == []


def test_pragma_without_justification_is_a_finding():
    f = _lint("""
        import time
        def f():
            return time.time()  # dopt: allow-wallclock
    """)
    assert _rules(f) == ["pragma"]


def test_unknown_pragma_rule_is_a_finding():
    f = _lint("""
        x = 1  # dopt: allow-everything -- please
    """)
    assert _rules(f) == ["pragma"]


def test_pragma_on_line_above_covers_continuation():
    f = _lint("""
        import time
        def f():
            # dopt: allow-wallclock -- span timing
            return time.time()
    """)
    assert f == []


def test_pragma_on_statement_continuation_line_covers():
    """A multi-line statement's pragma at its natural end-of-statement
    position suppresses a finding anchored at the first line."""
    f = _lint("""
        def report(tele):
            tele.emit("alert",
                      rule="x")  # dopt: allow-nondet-event -- documented
    """, path="dopt/engine/something.py")
    assert f == []


# ---------------------------------------------------------------------
# lint: unseeded-rng
# ---------------------------------------------------------------------

def test_global_numpy_rng_flagged_seeded_generator_clean():
    f = _lint("""
        import numpy as np
        def draw():
            a = np.random.rand(3)          # global state: flagged
            rng = np.random.default_rng(7)  # seeded: clean
            return a, rng.normal()
    """)
    assert _rules(f) == ["unseeded-rng"]


def test_seedless_default_rng_and_stdlib_random_flagged():
    f = _lint("""
        import numpy as np
        import random
        def draw():
            return np.random.default_rng(), random.choice([1, 2])
    """)
    assert _rules(f) == ["unseeded-rng", "unseeded-rng"]


def test_submodule_import_still_canonicalizes():
    """`import numpy.random` binds the top-level name `numpy`; the
    global-state API must still be recognized through it."""
    f = _lint("""
        import numpy.random
        def draw():
            return numpy.random.seed(0)
    """)
    assert _rules(f) == ["unseeded-rng"]


def test_seeded_seed_sequence_clean():
    f = _lint("""
        import numpy as np
        def draw(seed):
            return np.random.default_rng(np.random.SeedSequence([seed]))
    """)
    assert f == []


# ---------------------------------------------------------------------
# lint: trace-hazard
# ---------------------------------------------------------------------

def test_item_in_jitted_function_flagged():
    f = _lint("""
        import jax
        def step(x):
            return x.item()
        step_j = jax.jit(step)
    """)
    assert _rules(f) == ["trace-hazard"]


def test_item_outside_jit_clean():
    f = _lint("""
        def host_fetch(x):
            return x.item()
    """)
    assert f == []


def test_coercion_of_traced_param_in_scan_body_flagged():
    f = _lint("""
        from jax import lax
        def body(carry, x):
            n = int(x)
            return carry + n, n
        def run(xs):
            return lax.scan(body, 0, xs)
    """)
    assert _rules(f) == ["trace-hazard"]


def test_static_argnames_param_coercion_clean():
    f = _lint("""
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("lr",))
        def step(x, lr):
            return x * float(lr)
    """)
    assert f == []


def test_data_dependent_shape_in_jit_flagged():
    f = _lint("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def survivors(mask):
            return jnp.nonzero(mask)
    """)
    assert _rules(f) == ["trace-hazard"]


def test_reachability_through_local_helper():
    f = _lint("""
        import jax
        def helper(x):
            return x.item()
        def step(x):
            return helper(x)
        step_j = jax.jit(step)
    """)
    assert _rules(f) == ["trace-hazard"]


# ---------------------------------------------------------------------
# lint: nondet-event
# ---------------------------------------------------------------------

def test_nondet_kind_outside_obs_flagged():
    f = _lint("""
        def report(tele):
            tele.emit("alert", rule="x")
    """, path="dopt/engine/something.py")
    assert _rules(f) == ["nondet-event"]


def test_deterministic_kinds_clean_everywhere():
    f = _lint("""
        def report(tele):
            tele.emit("gauge", name="x", value=1.0)
            tele.emit("round", round=0)
            tele.emit("fault", worker=1)
            tele.emit("run", engine="gossip")
    """, path="dopt/engine/something.py")
    assert f == []


def test_nondet_kind_as_keyword_argument_flagged():
    f = _lint("""
        def report(tele):
            tele.emit(kind="resource", round=0)
    """, path="dopt/engine/something.py")
    assert _rules(f) == ["nondet-event"]


def test_bare_pragma_without_live_finding_still_flagged():
    """Stale or pre-placed bare pragmas fail even when they suppress
    nothing — the audit trail is unconditional."""
    f = _lint("""
        x = 1  # dopt: allow-wallclock
    """)
    assert _rules(f) == ["pragma"]


def test_obs_package_exempt_from_nondet_rule():
    f = _lint("""
        def fire(tele):
            tele.emit("alert", rule="x")
    """, path="dopt/obs/monitor.py")
    assert f == []


def test_real_tree_lints_clean():
    """The acceptance bar: `python -m dopt.analysis.lint dopt/` exits 0
    on the final tree, every pragma justified."""
    from dopt.analysis.lint import main

    assert main([str(REPO / "dopt")]) == EXIT_CLEAN


def test_lint_cli_exit_codes(tmp_path, capsys):
    from dopt.analysis.lint import main

    bad = tmp_path / "mod.py"
    bad.write_text("import time\n\ndef f():\n    return time.time()\n")
    assert main([str(bad)]) == EXIT_FINDINGS
    assert main([str(bad), "--rules", "nonsense"]) == EXIT_USAGE
    assert main([str(tmp_path / "missing.py")]) == EXIT_USAGE
    capsys.readouterr()
    assert main([str(bad), "--json"]) == EXIT_FINDINGS
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "dopt.analysis.lint" and not doc["clean"]
    assert doc["findings"][0]["rule"] == "wallclock"


# ---------------------------------------------------------------------
# eligibility: synthetic round-trip
# ---------------------------------------------------------------------

_SYNTH = '''
class Config:
    def __init__(self, a, b):
        if a and b:
            raise ValueError(
                f"feature a={a} does not compose with feature b "
                "(pick one) — drop one of the two")
        if a < 0:
            raise ValueError("a must be >= 0")

def run(x):
    if x is None:
        raise ValueError("x required at call time")
'''


def test_eligibility_harvest_and_classification(tmp_path):
    mod = tmp_path / "synth.py"
    mod.write_text(_SYNTH)
    art = harvest([str(mod)])
    assert art["counts"] == {"sites": 3, "construction": 2,
                             "composition": 1}
    comp = [s for s in art["sites"] if s["composition"]]
    assert len(comp) == 1
    assert comp[0]["scope"] == "Config.__init__"
    assert comp[0]["construction"]
    assert comp[0]["guard"] == "a and b"
    assert "{}" in comp[0]["message"]  # f-string hole survives as {}
    runtime = [s for s in art["sites"] if s["scope"] == "run"]
    assert runtime and not runtime[0]["construction"]


def test_eligibility_doc_table_roundtrip(tmp_path):
    mod = tmp_path / "synth.py"
    mod.write_text(_SYNTH)
    art = harvest([str(mod)])
    table = render_doc_table(art)
    doc = f"intro\n<!-- eligibility-matrix:begin -->\n{table}\n" \
          f"<!-- eligibility-matrix:end -->\nfooter\n"
    keys = parse_doc_rows(doc)
    comp = [s for s in art["sites"] if s["composition"]]
    assert keys == [doc_key(s) for s in comp]
    assert cross_check(art, art, keys, "art.json", "doc.md") == []


def test_eligibility_detects_both_drift_directions(tmp_path):
    mod = tmp_path / "synth.py"
    mod.write_text(_SYNTH)
    art = harvest([str(mod)])
    keys = [doc_key(s) for s in art["sites"] if s["composition"]]
    # Code grew a rejection the artifact/doc don't know.
    mod.write_text(_SYNTH + '''
class Late:
    def __init__(self, c, d):
        if c and d:
            raise ValueError("feature c is incompatible with feature d")
''')
    art2 = harvest([str(mod)])
    f = cross_check(art2, art, keys, "art.json", "doc.md")
    assert "artifact-stale" in _rules(f) and "code-without-doc" in _rules(f)
    # Doc kept a row whose rejection is gone from the code.
    f = cross_check(art, art, keys + ["vanished feature pair"],
                    "art.json", "doc.md")
    assert _rules(f) == ["doc-without-code"]


def test_site_key_ignores_line_drift(tmp_path):
    mod = tmp_path / "synth.py"
    mod.write_text(_SYNTH)
    a = harvest([str(mod)])
    mod.write_text("# shifted\n\n" + _SYNTH)
    b = harvest([str(mod)])
    assert [site_key(s) for s in a["sites"]] == \
        [site_key(s) for s in b["sites"]]
    assert [s["line"] for s in a["sites"]] != \
        [s["line"] for s in b["sites"]]


def test_committed_eligibility_artifacts_in_sync(monkeypatch, capsys):
    """The committed results/eligibility.json and the ARCHITECTURE.md
    matrix table both match the current tree (the CI gate, in-process)."""
    from dopt.analysis.eligibility import main

    monkeypatch.chdir(REPO)
    assert main(["--json"]) == EXIT_CLEAN
    doc = json.loads(capsys.readouterr().out)
    assert doc["clean"] and doc["counts"]["composition"] >= 30


# ---------------------------------------------------------------------
# fingerprint: sabotage must trip the gate, unchanged tree stays green
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def b1_fingerprint():
    from dopt.analysis.fingerprint import (canonical_matrix,
                                           compute_fingerprints)

    matrix = canonical_matrix()
    return compute_fingerprints({"baseline1-tiny":
                                 matrix["baseline1-tiny"]})


def test_fingerprint_unchanged_tree_green(b1_fingerprint):
    from dopt.analysis.fingerprint import (canonical_matrix,
                                           compute_fingerprints, diff)

    matrix = canonical_matrix()
    again = compute_fingerprints({"baseline1-tiny":
                                  matrix["baseline1-tiny"]})
    assert again == b1_fingerprint          # lowering is deterministic
    assert diff(again, b1_fingerprint, "reg.json") == []


def test_fingerprint_catches_default_knob_flip(b1_fingerprint):
    """Flip a default knob in a copy of the canonical config — the
    compiled program changes, the gate must fail."""
    from dopt.analysis.fingerprint import (canonical_matrix,
                                           compute_fingerprints, diff)

    base = canonical_matrix()["baseline1-tiny"]

    def sabotaged():
        cfg = base()
        return cfg.replace(optim=dataclasses.replace(cfg.optim,
                                                     lr=cfg.optim.lr * 2))

    sab = compute_fingerprints({"baseline1-tiny": sabotaged})
    findings = diff(sab, b1_fingerprint, "reg.json")
    assert _rules(findings) == ["fingerprint-mismatch"]
    assert "DEFAULT round program changed" in findings[0].message


def test_fingerprint_registry_env_gating(b1_fingerprint, tmp_path,
                                         monkeypatch, capsys):
    """Against a same-env registry the CLI compares (clean here); with
    an env mismatch it skips (exit 0) unless --strict."""
    from dopt.analysis.fingerprint import (current_env, main,
                                           write_registry)

    reg = tmp_path / "reg.json"
    committed = json.loads(
        (REPO / "results/program_fingerprints.json").read_text())
    write_registry(reg, committed["fingerprints"], current_env(),
                   "test bless")
    monkeypatch.chdir(REPO)
    if current_env() == committed["env"]:
        # Same env as the blessed registry: full byte comparison.
        assert main(["--registry", str(reg)]) == EXIT_CLEAN
    else:
        # Under the 8-device test mesh the registry env differs; pin
        # only the cheap single-program leg against a fresh same-env
        # registry instead of re-lowering the whole matrix.
        write_registry(reg, b1_fingerprint, current_env(), "test bless")
        assert main(["baseline1-tiny", "--registry",
                     str(reg)]) == EXIT_CLEAN
    # Env-mismatch skip vs --strict fail.
    write_registry(reg, committed["fingerprints"],
                   {"jax": "0.0.0", "backend": "none", "devices": 0},
                   "stale env")
    capsys.readouterr()
    assert main(["baseline1-tiny", "--registry", str(reg),
                 "--json"]) == EXIT_CLEAN
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "skipped"
    # Text mode must SAY it skipped, not report a hollow "clean".
    assert main(["baseline1-tiny", "--registry", str(reg)]) == EXIT_CLEAN
    out = capsys.readouterr().out
    assert "SKIPPED" in out and "environment mismatch" in out
    assert main(["baseline1-tiny", "--registry", str(reg),
                 "--strict"]) == EXIT_FINDINGS
    # Partial bless under a foreign env is refused (would stamp stale
    # hashes with the wrong env).
    assert main(["baseline1-tiny", "--bless", "--reason", "x",
                 "--registry", str(reg)]) == EXIT_USAGE


def test_fingerprint_bless_requires_reason(capsys):
    from dopt.analysis.fingerprint import main

    assert main(["--bless"]) == EXIT_USAGE


def test_fingerprint_canonicalize_strips_locations():
    from dopt.analysis.fingerprint import canonicalize

    text = ('module @jit_f {\n'
            '  %0 = add loc("eng.py":12:0)  \n'
            '#loc1 = loc("eng.py":40:2)\n}\n')
    out = canonicalize(text)
    assert "loc(" not in out and "#loc" not in out
    assert "%0 = add" in out


# ---------------------------------------------------------------------
# shared conventions
# ---------------------------------------------------------------------

def test_parse_pragmas_extracts_rule_and_justification():
    src = "x = 1  # dopt: allow-wallclock -- because telemetry\n" \
          "y = 2  # dopt: allow-unseeded-rng\n"
    pragmas = parse_pragmas(src)
    assert pragmas[1][0].rule == "wallclock"
    assert pragmas[1][0].justification == "because telemetry"
    assert pragmas[2][0].justification is None


def test_obs_check_json_convention(tmp_path, capsys):
    """dopt.obs.check speaks the same --json + exit-code contract as
    the analysis CLIs."""
    from dopt.obs.check import main

    good = tmp_path / "ok.jsonl"
    good.write_text(
        '{"v": 1, "kind": "run", "ts": 1.0, "engine": "gossip", '
        '"name": "x", "round": 0, "workers": 2}\n'
        '{"v": 1, "kind": "round", "ts": 2.0, "engine": "gossip", '
        '"round": 0, "metrics": {"loss": 1.5}}\n')
    assert main([str(good), "--json"]) == EXIT_CLEAN
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "dopt.obs.check" and doc["clean"]
    assert doc["files"][0]["ok"]
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v": 1, "kind": "nope", "ts": 1.0}\n')
    assert main([str(bad), "--json"]) == EXIT_FINDINGS
    doc = json.loads(capsys.readouterr().out)
    assert not doc["clean"] and not doc["files"][0]["ok"]
