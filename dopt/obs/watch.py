"""Live terminal tail over a growing metrics file: ``python -m dopt.obs.watch``.

The at-a-glance view of a run *while it trains*: rounds/sec (from the
round events' wall clocks), the loss curve's latest point, fleet gauges
(quarantine load, consensus distance), fault counts, the latest phase
fractions, and every health alert the attached ``HealthMonitor`` fires
— all from incremental polls of the JSONL stream (byte-offset tail, so
a million-round file costs nothing to keep watching).

Stdlib-only (no jax): run it on a laptop against a file scp'd or
streamed off the training host::

    python -m dopt.obs.watch metrics.jsonl            # live, 2s refresh
    python -m dopt.obs.watch metrics.jsonl --once     # one snapshot
    python -m dopt.obs.watch --state-dir run/         # FLEET mode

Fleet mode (``--state-dir``) tails every process's stream of a
``dopt serve --num-processes N`` state dir through the
``FleetAggregator``: one terminal view with per-process rounds/s and
loss columns, the cross-process consistency verdict, the merged alert
feed with process provenance, and the admin endpoint read from the
daemon's ``serve.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque
from pathlib import Path
from typing import Any

from dopt.obs.monitor import HealthMonitor, JsonlTail
from dopt.obs.rules import loss_of

# Wall-clock window (round events) for the rounds/sec estimate.
_RATE_WINDOW = 32


class WatchState:
    """Incremental reduction of the event stream into one screenful.

    ``gauge_filter`` (a set of gauge names, or None) narrows the gauge
    line; by DEFAULT every gauge in the stream renders — new producer
    gauges (the ``diagnostics="on"`` convergence block, future
    engines') surface without a code edit here."""

    def __init__(self, monitor: HealthMonitor,
                 gauge_filter: set[str] | None = None):
        self.monitor = monitor
        self.gauge_filter = gauge_filter
        self.tail: JsonlTail | None = None
        self.run: dict[str, Any] | None = None
        self.round: int | None = None
        self.loss_key: str | None = None
        self.loss: float | None = None
        self.metrics: dict[str, Any] = {}
        self.gauges: dict[str, float] = {}
        self.faults: dict[str, int] = {}
        self.phases: dict[str, float] | None = None
        self.resource: dict[str, Any] | None = None
        self.compiles = 0
        self.events = 0
        # Alerts EMBEDDED in the stream (a producer-side monitor wrote
        # them) — kept separate from self.monitor's own firings, which
        # may use different rule parameters.
        self.stream_alerts: list[dict[str, Any]] = []
        self._round_ts: deque[float] = deque(maxlen=_RATE_WINDOW)

    def poll(self, path: str) -> list[dict[str, Any]]:
        """Feed the events appended to ``path`` since the last poll
        (byte-offset tail); returns the alerts they fired."""
        if self.tail is None:
            self.tail = JsonlTail(path)
        return self.feed(self.tail.poll())

    def feed(self, events: list[dict[str, Any]]) -> list[dict[str, Any]]:
        """Consume a poll's events; returns the alerts fired by it."""
        fired: list[dict[str, Any]] = []
        for ev in events:
            self.events += 1
            fired.extend(self.monitor.observe(ev))
            kind = ev.get("kind")
            if kind == "run":
                self.run = ev
            elif kind == "round":
                self.round = ev.get("round")
                self.metrics = ev.get("metrics", {})
                k, v = loss_of(self.metrics)
                if k is not None:
                    self.loss_key, self.loss = k, v
                ts = ev.get("ts")
                if isinstance(ts, (int, float)):
                    self._round_ts.append(float(ts))
            elif kind == "gauge":
                self.gauges[str(ev.get("name"))] = float(ev.get("value", 0))
            elif kind == "fault":
                f = str(ev.get("fault"))
                self.faults[f] = self.faults.get(f, 0) + 1
            elif kind == "phase":
                self.phases = ev.get("fractions")
            elif kind == "resource":
                self.resource = ev
            elif kind == "compile":
                self.compiles += 1
            elif kind == "alert":
                self.stream_alerts.append(ev)
        return fired

    def all_alerts(self) -> list[dict[str, Any]]:
        """Stream-embedded alerts plus this watcher's own firings,
        minus own firings that duplicate an embedded one (same rule at
        the same round — the producer's monitor and the stock local
        rules re-deriving the same condition from the same events)."""
        seen = {(a.get("rule"), a.get("round"), a.get("severity"))
                for a in self.stream_alerts}
        return self.stream_alerts + [
            a for a in self.monitor.alerts
            if (a.get("rule"), a.get("round"), a.get("severity"))
            not in seen]

    def critical(self) -> bool:
        """Any critical alert, embedded in the stream or fired by this
        watcher's own monitor."""
        return any(a.get("severity") == "critical"
                   for a in self.all_alerts())

    def rounds_per_sec(self) -> float | None:
        ts = self._round_ts
        if len(ts) < 2 or ts[-1] <= ts[0]:
            return None
        return (len(ts) - 1) / (ts[-1] - ts[0])

    def render(self) -> str:
        lines = []
        run = self.run or {}
        head = (f"dopt watch — {run.get('name', '?')} "
                f"[{run.get('engine', '?')}"
                + (f", {run['workers']} workers" if run.get("workers")
                   else "") + "]")
        lines.append(head)
        rps = self.rounds_per_sec()
        lines.append(
            f"  round {self.round if self.round is not None else '-'}"
            + (f" @ {rps:.3f} rounds/s" if rps else "")
            + (f" | {self.loss_key}={self.loss:.5g}"
               if self.loss is not None and self.loss_key else
               (f" | {self.loss_key}=non-finite" if self.loss_key else "")))
        # ALL gauges render by default (sorted, %g-formatted) so new
        # producer gauges — the diagnostics="on" convergence block
        # included — surface without a code edit; --gauges narrows.
        shown = self.gauges
        if self.gauge_filter is not None:
            shown = {k: v for k, v in shown.items()
                     if k in self.gauge_filter}
        if shown:
            lines.append("  gauges  " + "  ".join(
                f"{k}={v:g}" for k, v in sorted(shown.items())))
        if self.resource is not None:
            peak = self.resource.get("peak_bytes")
            live = self.resource.get("live_bytes")
            bits = [f"peak={peak / 2**30:.2f}GiB"
                    if isinstance(peak, (int, float)) else None,
                    f"live={live / 2**30:.2f}GiB"
                    if isinstance(live, (int, float)) else None,
                    (f"({self.resource.get('source')})"
                     if self.resource.get("source") else None),
                    f"compiles={self.compiles}" if self.compiles else None]
            lines.append("  memory  " + "  ".join(b for b in bits if b))
        if self.faults:
            lines.append("  faults  " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.faults.items())))
        if self.phases:
            lines.append("  phases  " + "  ".join(
                f"{k}={v:.0%}" for k, v in sorted(self.phases.items())))
        rep = self.monitor.report()
        alerts = self.all_alerts()
        verdict = "CRITICAL" if self.critical() else \
            ("WARN" if alerts else rep.verdict.upper())
        lines.append(f"  health  {verdict} "
                     f"({len(alerts)} alerts, {rep.rounds} rounds, "
                     f"{self.events} events)")
        for a in alerts[-5:]:
            lines.append(f"  ALERT [{a.get('severity')}] "
                         f"{a.get('rule')} @ round {a.get('round')}: "
                         f"{a.get('message')}")
        return "\n".join(lines)


class FleetWatchState:
    """One screenful over a whole serve fleet's streams, built on the
    ``FleetAggregator``: per-process round/rate/loss/lag rows, the
    cross-process consistency verdict, and the merged alert feed with
    process provenance."""

    def __init__(self, state_dir: str, processes: int | None = None):
        self.state_dir = Path(state_dir)
        self._processes = processes
        self.error: str | None = None
        self.status: dict[str, Any] = {}   # serve.json, one read per tick
        self._refresh_status()
        self.agg = self._build()

    def _build(self):
        from dopt.obs.aggregate import FleetAggregator

        return FleetAggregator(self.state_dir,
                               num_processes=self._expected())

    def _refresh_status(self) -> None:
        """ONE status read per tick (serve.json, falling back to the
        supervisor's fleet.json), shared by the expected-fleet-size
        probe and the render header — the state dir may be remote."""
        for name in ("serve.json", "fleet.json"):
            try:
                self.status = json.loads(
                    (self.state_dir / name).read_text())
                return
            except (OSError, ValueError):
                continue
        self.status = {}

    def _expected(self) -> int | None:
        """Expected fleet size: the explicit --processes, else the
        daemon's own status-file claim — so a watch started before
        follower streams exist still waits for them instead of
        silently degrading to a leader-only 'consistency ok'."""
        if self._processes is not None:
            return self._processes
        n = self.status.get("num_processes")
        if isinstance(n, int) and n >= 1:
            return n
        return None   # glob discovery (single-process dirs)

    def poll(self) -> None:
        self._refresh_status()
        expected = self._expected()
        if expected is not None and expected > len(self.agg.processes):
            # Followers appeared (or the daemon finally wrote its
            # status) after we built the aggregator: rebuild over the
            # full fleet — a restarted merge beats a silent
            # leader-only view.
            self.agg = self._build()
        try:
            self.agg.poll()
            self.error = None
        except ValueError as e:
            # Mid-file garbage: render the error, keep watching.
            self.error = str(e)
        # The live watch consumes stats()/alerts(), never the merged
        # event list — drop it, or a days-long watch of a resident
        # fleet retains every event of every process in memory.
        self.agg.drain_merged()

    def critical(self) -> bool:
        return (self.agg.divergence is not None
                or any(a.get("severity") == "critical"
                       for a in self.agg.alerts()))

    def render(self) -> str:
        from dopt.obs.aggregate import format_fleet_divergence

        now = time.time()  # dopt: allow-wallclock -- lag column vs event ts stamps, display only
        stats = self.agg.stats(now)
        status = self.status
        head = f"dopt fleet watch — {self.state_dir}"
        bits = []
        if status.get("status"):
            bits.append(status["status"])
        if status.get("admin_port"):
            bits.append(f"admin :{status['admin_port']}")
        if stats["fleet_round"] is not None:
            bits.append(f"fleet round {stats['fleet_round']}")
        if bits:
            head += "  [" + ", ".join(bits) + "]"
        lines = [head]
        if self.error:
            lines.append(f"  STREAM ERROR: {self.error}")
        lines.append("  proc  round     rounds/s  loss          "
                     "lag(s)  segs  alerts")
        for p, snap in sorted(stats["processes"].items()):
            loss = snap["loss"]
            rps = snap["rounds_per_sec"]
            lag = snap["lag_seconds"]
            lines.append(
                f"  p{p:<4} "
                f"{str('-' if snap['round'] is None else snap['round']):<9} "
                f"{f'{rps:.3f}' if rps else '-':<9} "
                f"{f'{loss:.6g}' if isinstance(loss, (int, float)) else '-':<13} "
                f"{f'{lag:.1f}' if lag is not None else '-':<7} "
                f"{snap['segments']:<5} {snap['alerts']}")
        if self.agg.divergence is not None:
            lines.append("  CONSISTENCY: DIVERGED")
            lines.extend("  " + line for line in
                         format_fleet_divergence(self.agg.divergence)
                         .splitlines())
        else:
            lines.append(f"  consistency ok through round "
                         f"{stats['fleet_round'] if stats['fleet_round'] is not None else '-'} "
                         f"({stats['rounds_merged']} rounds verified, "
                         f"{stats['merged_events']} merged events)")
        alerts = self.agg.alerts()
        for a in alerts[-5:]:
            lines.append(f"  ALERT [{a.get('severity')}] "
                         f"p{a.get('process')} {a.get('rule')} @ round "
                         f"{a.get('round')}: {a.get('message')}")
        return "\n".join(lines)


def watch_fleet(args) -> int:
    state = FleetWatchState(args.state_dir, processes=args.processes)
    try:
        while True:
            state.poll()
            if args.once:
                print(state.render())
                # Corrupt streams fail the exit-code contract too:
                # check/aggregate exit 1 on the same dir, so must the
                # scripted one-shot watch.
                return 1 if (state.critical()
                             or state.error is not None) else 0
            if not args.no_clear:
                sys.stdout.write("\x1b[H\x1b[2J")
            print(state.render(), flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        print()
        return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("metrics", nargs="?", default=None,
                    metavar="METRICS_JSONL")
    ap.add_argument("--state-dir", default=None, metavar="DIR",
                    help="FLEET mode: watch every process stream of a "
                         "dopt serve state dir (metrics.jsonl + "
                         "metrics-p<i>.jsonl), one merged view with "
                         "per-process columns and alert provenance")
    ap.add_argument("--processes", type=int, default=None, metavar="N",
                    help="fleet mode: expected fleet size (default: "
                         "discover follower streams by glob)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds")
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot of the current file and "
                         "exit (CI / scripting mode)")
    ap.add_argument("--no-clear", action="store_true",
                    help="append snapshots instead of redrawing in "
                         "place (for dumb terminals / logs)")
    ap.add_argument("--workers", type=int, default=None,
                    help="fleet-size denominator override for rules")
    ap.add_argument("--gauges", default=None, metavar="NAME[,NAME...]",
                    help="show only these gauges (comma-separated); "
                         "default shows every gauge in the stream")
    args = ap.parse_args(argv)

    if args.state_dir is not None:
        return watch_fleet(args)
    if args.metrics is None:
        ap.error("give a METRICS_JSONL path or --state-dir")

    monitor = HealthMonitor(workers=args.workers)
    gauge_filter = (set(g.strip() for g in args.gauges.split(",")
                        if g.strip())
                    if args.gauges else None)
    state = WatchState(monitor, gauge_filter=gauge_filter)
    try:
        while True:
            fired = state.poll(args.metrics)
            if args.once:
                print(state.render())
                return 1 if state.critical() else 0
            if not args.no_clear:
                # Home + clear-to-end: redraw in place without
                # scrollback spam.
                sys.stdout.write("\x1b[H\x1b[2J")
            print(state.render(), flush=True)
            for a in fired:
                # New alerts also go to stderr so a piped log keeps them.
                print(f"ALERT [{a.get('severity')}] {a.get('rule')} "
                      f"@ round {a.get('round')}: {a.get('message')}",
                      file=sys.stderr)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        print()
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
