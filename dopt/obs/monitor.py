"""Streaming run-health monitor over the dopt.obs event stream.

``HealthMonitor`` consumes the v1 event stream *while a run trains* and
evaluates a declarative rule set (dopt.obs.rules), emitting ``alert``
events and an end-of-run ``HealthReport`` verdict.  Two attachment
modes, one ``observe(event)`` core:

* **in-process** — the monitor is a ``Sink``: ``monitor.attach(tele)``
  appends it to a ``Telemetry``'s sink list, so every round bundle the
  engines emit flows through the rules as it happens, and fired alerts
  are forwarded to the OTHER sinks (they land in the JSONL stream just
  after the round that triggered them);
* **tailing** — ``monitor.poll_file(path)`` incrementally reads a
  growing JSONL metrics file (complete lines only, byte-offset
  watermark), the ``scan_watermark``-style resume: a monitor restarted
  from ``monitor.state()`` continues where it stopped without
  re-firing a single alert.

Because rules read only the deterministic kinds (round/gauge/fault)
plus run headers, the alert sequence is identical for per-round,
fused-blocked and killed-and-resumed execution of the same config —
the canonical-stream guarantee lifted to alerts (chaos soak pins it on
real runs, tests/test_monitor.py on synthetic streams).

Stdlib-only: tailing a metrics file must not drag jax onto a laptop.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Iterable

from dopt.obs.events import make_event, validate_event
from dopt.obs.latency import LatencyHistogram
from dopt.obs.rules import Rule, RunContext, default_rules
from dopt.obs.sinks import Sink

# Fields of an alert event that identify it across executions —
# everything but the wall clock.
_ALERT_CANON_DROP = ("ts",)


class JsonlTail:
    """Incremental JSONL reader with a byte-offset watermark.

    ``poll()`` returns the complete-line events appended since the last
    poll and advances the offset past them; a trailing partial line (a
    writer mid-flush, or the torn tail a SIGKILL leaves) stays pending
    until its newline lands, so a tailer never parses half an event.
    A complete line that is not JSON raises — mid-file garbage means
    the file is corrupt, and silently skipping it would desynchronize
    every downstream consumer."""

    def __init__(self, path: str | Path, offset: int = 0):
        self.path = Path(path)
        self.offset = int(offset)

    def poll(self) -> list[dict[str, Any]]:
        if not self.path.exists():
            return []
        with open(self.path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            if size < self.offset:
                # The file SHRANK below our watermark —
                # JsonlSink.repair_tail does this on kill-and-resume
                # when it drops the torn tail / orphan lines of an
                # unsealed bundle.  Clamp to the new end: the removed
                # bytes were already consumed, and everything the
                # resumed producer appends lands after this point.
                # (Orphan fault/gauge rows of a torn bundle may thus be
                # seen twice — pre-repair and re-emitted — but their
                # bundle's round event only ever seals once.)
                self.offset = size
            f.seek(self.offset)
            chunk = f.read()
        if not chunk:
            return []
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        events: list[dict[str, Any]] = []
        for i, line in enumerate(chunk[:end + 1].splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                raise ValueError(
                    f"{self.path}: offset {self.offset}, line {i + 1} is "
                    f"not JSON: {line[:80]!r}")
        self.offset += end + 1
        return events


@dataclasses.dataclass
class HealthReport:
    """End-of-run verdict: what a soak's CI gate (and ``/healthz``)
    consume.  ``verdict``: 'healthy' (no alerts), 'warn' (only warn-
    severity alerts), 'critical', or 'empty' (no rounds observed)."""

    verdict: str
    rounds: int
    segments: int
    alerts: int
    by_rule: dict[str, int]
    by_severity: dict[str, int]
    last_round: int | None
    engines: list[str]
    # SLO latency summaries (p50/p95/p99 per latency name) folded from
    # the stream's ``latency`` events plus the monitor's own measured
    # alert latency — what the soak's SLO report and ``final.json``
    # carry.  Empty when the stream carries no latency channel.
    latency: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.verdict in ("healthy", "warn", "empty")

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def write(self, path: str | Path) -> Path:
        from dopt.utils.metrics import atomic_write_text

        return atomic_write_text(path, json.dumps(self.to_dict(), indent=2))


class HealthMonitor(Sink):
    """Evaluates a rule set over an event stream; collects alerts.

    As a ``Sink`` it can sit in a ``Telemetry``'s fan-out (use
    ``attach`` so fired alerts are forwarded to the other sinks); as a
    tailer it polls a JSONL file.  ``state()``/``state=`` checkpoint
    and resume the whole thing — rule windows included — so a
    restarted tail never duplicates an alert."""

    def __init__(self, rules: list[Rule] | None = None, *,
                 workers: int | None = None,
                 state: dict[str, Any] | None = None):
        self.rules = rules if rules is not None else default_rules()
        self.alerts: list[dict[str, Any]] = []
        self.ctx = RunContext(workers=workers)
        self.rounds_seen = 0
        self.segments = 0
        self._engines: list[str] = []
        self._by_rule: dict[str, int] = {}
        self._by_severity: dict[str, int] = {}
        # SLO latency histograms: per-name fixed-bucket histograms fed
        # from the stream's ``latency`` events, plus the monitor's own
        # ``alert_latency`` measurement (triggering round bundle ts →
        # alert emit ts, taken at fire time).  JSON-able, part of
        # ``state()`` like the rule windows — a restarted monitor keeps
        # accumulating instead of forgetting the run's tail latencies.
        self.latency: dict[str, LatencyHistogram] = {}
        # Wall-clock staleness meters: the ts of the newest event seen
        # (any kind) and of the newest round event — /healthz reports
        # "last event ts vs wall" so a stalled producer is
        # distinguishable from a healthy idle one.
        self.last_event_ts: float | None = None
        self._last_round_ts: float | None = None
        self._telemetry = None
        self._tail: JsonlTail | None = None
        self._tail_offset = 0
        if state is not None:
            self.load_state(state)

    # -- consumption ---------------------------------------------------
    def emit(self, event: dict[str, Any]) -> None:
        """Sink protocol: evaluate the event (alerts accumulate on the
        monitor and are forwarded to the attached Telemetry's other
        sinks)."""
        self.observe(event)

    def observe(self, ev: dict[str, Any]) -> list[dict[str, Any]]:
        """Evaluate one event against every rule; returns the alert
        events fired (schema-stamped, already recorded)."""
        kind = ev.get("kind")
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            self.last_event_ts = (float(ts) if self.last_event_ts is None
                                  else max(self.last_event_ts, float(ts)))
        if kind == "alert":
            return []   # never feed alerts back into the rules
        if kind == "latency":
            # The SLO latency channel: accumulate into the per-name
            # histograms the HealthReport summarizes.  No rule reads
            # the kind (wall-clock durations), so fall through is safe
            # but pointless.
            v = ev.get("seconds")
            if isinstance(v, (int, float)) and v >= 0:
                self.latency.setdefault(
                    str(ev.get("name", "?")),
                    LatencyHistogram()).observe(float(v))
            return []
        if kind == "run":
            self.ctx.engine = ev.get("engine")
            if isinstance(ev.get("workers"), int):
                self.ctx.workers = ev["workers"]
            if int(ev.get("round", 0)) == 0:
                # A fresh logical run (bench legs share one file); a
                # header with round > 0 is a resume CONTINUATION and
                # must keep the rule windows — the resumed stream's
                # alerts must match the continuous run's.
                self.segments += 1
                self.ctx.cohort = None
                self.ctx.population = None
                self.ctx.participating = None
                self.ctx.checkpoint_every = None
                for r in self.rules:
                    r.reset()
            elif self.segments == 0:
                self.segments = 1
            ce = ev.get("checkpoint_every")
            if isinstance(ce, int) and not isinstance(ce, bool):
                # The run's configured checkpoint cadence, read by the
                # checkpoint_cadence rule; resume continuations restate
                # it, so a restarted tail keeps monitoring the cadence.
                self.ctx.checkpoint_every = ce
        elif kind == "control":
            # A served run's applied control-plane commands: a cadence
            # change moves the checkpoint_cadence rule's expectation
            # from the boundary it was applied.
            if (ev.get("cmd") == "config"
                    and ev.get("key") == "checkpoint_every"
                    and isinstance(ev.get("value"), (int, float))):
                self.ctx.checkpoint_every = int(ev["value"]) or None
        elif kind == "round":
            self.rounds_seen += 1
            self.ctx.round = int(ev["round"])
            if isinstance(ts, (int, float)):
                self._last_round_ts = float(ts)
        elif kind == "gauge":
            # Denominator gauges the engines emit for the
            # fleet-fraction rules.
            name = ev.get("name")
            if name == "cohort_size":
                self.ctx.cohort = float(ev["value"])
            elif name == "population_size":
                self.ctx.population = float(ev["value"])
            elif name == "participating_lanes":
                self.ctx.participating = float(ev["value"])
        fired: list[dict[str, Any]] = []
        extras: list[dict[str, Any]] = []
        for rule in self.rules:
            for payload in rule.update(ev, self.ctx):
                alert = self._record(rule, payload)
                fired.append(alert)
                lat = self._alert_latency(alert, ev)
                if lat is not None:
                    extras.append(lat)
        if (fired or extras) and self._telemetry is not None:
            for s in self._telemetry.sinks:
                if s is not self:
                    s.emit_many(fired + extras)
        return fired

    def _alert_latency(self, alert: dict[str, Any],
                       trigger: dict[str, Any]) -> dict[str, Any] | None:
        """Measure one alert's latency — the TRIGGERING event's ``ts``
        (the gauge/round/fault of its bundle that tripped the rule; a
        gauge-driven rule fires before the bundle's round event lands,
        so the previous round event would overstate by a full round
        interval) to the alert event's ``ts``, both stamped by the same
        producer clock — into the ``alert_latency`` histogram, and
        return the ``latency`` event to forward into the stream.  ONLY
        measured when the monitor rides the live fan-out (``attach``):
        a tail/replay-fed monitor (fleet endpoint, watch, an offline
        soak gate) observes historical ``ts`` stamps, so "alert now
        minus event then" would report poll cadence, not alert latency
        — those consumers get the channel from the stream's own
        embedded latency events instead (the ``latency``-kind branch
        above)."""
        if self._telemetry is None:
            return None
        ts = alert.get("ts")
        anchor = trigger.get("ts")
        if not isinstance(anchor, (int, float)):
            anchor = self._last_round_ts
        if anchor is None or not isinstance(ts, (int, float)):
            return None
        lat = max(0.0, float(ts) - float(anchor))
        self.latency.setdefault("alert_latency",
                                LatencyHistogram()).observe(lat)
        return make_event("latency", round=int(alert.get("round", 0)),
                          name="alert_latency", seconds=round(lat, 6))

    def lag_seconds(self, now: float | None = None) -> float | None:
        """Wall seconds since the newest event this monitor has seen —
        the "is the producer stalled or just idle" meter /healthz
        reports; None before any event."""
        if self.last_event_ts is None:
            return None
        if now is None:
            import time

            now = time.time()  # dopt: allow-wallclock -- staleness meter vs the event ts stamps, reporting only
        return max(0.0, float(now) - self.last_event_ts)

    def _record(self, rule: Rule, payload: dict[str, Any]) -> dict[str, Any]:
        ev = make_event(
            "alert",
            round=int(payload.get("round", max(self.ctx.round, 0))),
            rule=rule.name, severity=rule.severity,
            message=str(payload.get("message", rule.name)),
            value=payload.get("value"),
            engine=self.ctx.engine)
        validate_event(ev)
        self.alerts.append(ev)
        self._by_rule[rule.name] = self._by_rule.get(rule.name, 0) + 1
        self._by_severity[rule.severity] = \
            self._by_severity.get(rule.severity, 0) + 1
        eng = self.ctx.engine
        if eng and eng not in self._engines:
            self._engines.append(eng)
        return ev

    def feed(self, events: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
        """Batch observe; returns all alerts fired by the batch."""
        fired: list[dict[str, Any]] = []
        for ev in events:
            fired.extend(self.observe(ev))
        return fired

    def poll_file(self, path: str | Path) -> list[dict[str, Any]]:
        """Tail a growing JSONL stream: process only the bytes
        appended since the previous poll (complete lines only).  The
        offset is part of ``state()`` — a monitor rebuilt from saved
        state resumes the tail exactly where it stopped."""
        path = Path(path)
        if self._tail is None or self._tail.path != path:
            self._tail = JsonlTail(path, offset=self._tail_offset)
        fired = self.feed(self._tail.poll())
        self._tail_offset = self._tail.offset
        return fired

    # -- attachment ----------------------------------------------------
    def attach(self, telemetry) -> "HealthMonitor":
        """Join a ``Telemetry``'s sink fan-out (appended LAST, so a
        round bundle reaches the file/ring sinks before any alert it
        triggers) and forward fired alerts to the other sinks."""
        self._telemetry = telemetry
        if self not in telemetry.sinks:
            telemetry.sinks.append(self)
        return self

    # -- state (resume) ------------------------------------------------
    def state(self) -> dict[str, Any]:
        """JSON-able checkpoint of the monitor: tail offset, counters,
        context, and every rule's windowed state."""
        return {
            "v": 1,
            "offset": (self._tail.offset if self._tail is not None
                       else self._tail_offset),
            "rounds_seen": self.rounds_seen,
            "segments": self.segments,
            "engines": list(self._engines),
            "by_rule": dict(self._by_rule),
            "by_severity": dict(self._by_severity),
            "ctx": {"engine": self.ctx.engine, "workers": self.ctx.workers,
                    "cohort": self.ctx.cohort,
                    "population": self.ctx.population,
                    "participating": self.ctx.participating,
                    "checkpoint_every": self.ctx.checkpoint_every,
                    "round": self.ctx.round},
            "rules": {r.name: json.loads(json.dumps(r.s))
                      for r in self.rules},
            "latency": {name: h.state()
                        for name, h in self.latency.items()},
            "last_event_ts": self.last_event_ts,
            "last_round_ts": self._last_round_ts,
        }

    def load_state(self, st: dict[str, Any]) -> None:
        self._tail_offset = int(st.get("offset", 0))
        self._tail = None
        self.rounds_seen = int(st.get("rounds_seen", 0))
        self.segments = int(st.get("segments", 0))
        self._engines = list(st.get("engines", []))
        self._by_rule = dict(st.get("by_rule", {}))
        self._by_severity = dict(st.get("by_severity", {}))
        ctx = st.get("ctx", {})
        self.ctx.engine = ctx.get("engine")
        self.ctx.workers = ctx.get("workers")
        self.ctx.cohort = ctx.get("cohort")
        self.ctx.population = ctx.get("population")
        self.ctx.participating = ctx.get("participating")
        self.ctx.checkpoint_every = ctx.get("checkpoint_every")
        self.ctx.round = int(ctx.get("round", -1))
        saved = st.get("rules", {})
        for r in self.rules:
            if r.name in saved:
                r.s = dict(saved[r.name])
        self.latency = {name: LatencyHistogram.from_state(hs)
                        for name, hs in st.get("latency", {}).items()}
        self.last_event_ts = st.get("last_event_ts")
        self._last_round_ts = st.get("last_round_ts")

    # -- results -------------------------------------------------------
    def canonical_alerts(self) -> list[dict[str, Any]]:
        """Alerts minus wall-clock fields — the comparison form for the
        per-round vs blocked vs resumed equality invariant."""
        return [{k: v for k, v in a.items() if k not in _ALERT_CANON_DROP}
                for a in self.alerts]

    def report(self) -> HealthReport:
        if self.rounds_seen == 0 and not self.alerts:
            verdict = "empty"
        elif self._by_severity.get("critical"):
            verdict = "critical"
        elif self.alerts:
            verdict = "warn"
        else:
            verdict = "healthy"
        return HealthReport(
            verdict=verdict, rounds=self.rounds_seen,
            segments=self.segments, alerts=len(self.alerts),
            by_rule=dict(self._by_rule),
            by_severity=dict(self._by_severity),
            last_round=self.ctx.round if self.ctx.round >= 0 else None,
            engines=list(self._engines),
            latency={name: h.summary()
                     for name, h in sorted(self.latency.items())})
