// Native host-side batch planning for the dopt data layer.
//
// The TPU engines consume per-round [workers, steps, batch] gather-index
// plans (dopt/data/pipeline.py).  Generating those plans is the only
// per-round host-side loop in the framework; this library fills the plan
// buffers in C++ (one Fisher-Yates shuffle per (round, epoch, worker))
// so large fleets (hundreds of workers × many local epochs) never
// bottleneck on the Python/numpy loop.
//
// Determinism: a SplitMix64-seeded xoshiro256** stream per
// (seed, round_idx, epoch, worker) — reproducible across runs and
// platforms, but intentionally NOT bit-identical to the numpy
// PCG64 path (the numpy path remains the torch-oracle-parity mode;
// this is the throughput mode).  Same contract otherwise: every epoch
// block is a permutation of the worker's index row, wraparound padding
// with 0-weight mask tail.
//
// Build: g++ -O3 -shared -fPIC plan.cpp -o libdopt_host.so   (see
// dopt/native/__init__.py, which builds lazily and caches).

#include <cstdint>
#include <cstring>

namespace {

// SplitMix64: seeds the xoshiro state from a packed key.
inline uint64_t splitmix64(uint64_t &x) {
  x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

struct Xoshiro256ss {
  uint64_t s[4];

  explicit Xoshiro256ss(uint64_t seed) {
    uint64_t sm = seed;
    for (int i = 0; i < 4; ++i) s[i] = splitmix64(sm);
  }

  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }

  inline uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }

  // Unbiased bounded draw (Lemire's method).
  inline uint64_t bounded(uint64_t n) {
    uint64_t x = next();
    __uint128_t m = (__uint128_t)x * (__uint128_t)n;
    uint64_t l = (uint64_t)m;
    if (l < n) {
      uint64_t t = (0ULL - n) % n;
      while (l < t) {
        x = next();
        m = (__uint128_t)x * (__uint128_t)n;
        l = (uint64_t)m;
      }
    }
    return (uint64_t)(m >> 64);
  }
};

inline uint64_t mix_key(int64_t seed, int64_t round_idx, int64_t ep,
                        int64_t worker) {
  // Feed the four key components through SplitMix64 sequentially — the
  // same construction style as numpy's SeedSequence (hash-mix of an
  // entropy list), collision-free in practice for experiment-sized keys.
  uint64_t x = 0x243F6A8885A308D3ULL;  // pi fraction, arbitrary non-zero
  uint64_t acc = splitmix64(x) ^ (uint64_t)seed;
  x = acc;
  acc = splitmix64(x) ^ (uint64_t)round_idx;
  x = acc;
  acc = splitmix64(x) ^ (uint64_t)ep;
  x = acc;
  acc = splitmix64(x) ^ (uint64_t)worker;
  return acc;
}

}  // namespace

extern "C" {

// Fill one round's plan.
//   index_matrix : [num_workers, row_len] int32 per-worker dataset indices
//   worker_ids   : nullable [num_workers] int64 — the TRUE worker id of each
//                  row, used as the RNG key component.  Null means row i is
//                  worker i.  Passing a subset of rows with their real ids
//                  yields plans bit-identical to the matching rows of the
//                  full-fleet plan (compact-sampling fast path).
//   idx_out      : [num_workers, local_ep * steps_per_epoch, batch] int32
//   w_out        : [num_workers, local_ep * steps_per_epoch, batch] float32
// steps_per_epoch = ceil(row_len / batch) (drop_last=0) or
//                   row_len / batch       (drop_last=1), computed by caller;
// padded tail (drop_last=0) wraps around with weight 0.
// scratch: caller-provided [row_len + pad] int32 workspace per thread
// (we allocate internally instead to keep the ABI simple).
// Returns 0 on success, nonzero on bad arguments.
int dopt_fill_batch_plan(const int32_t *index_matrix, int64_t num_workers,
                         int64_t row_len, int64_t batch, int64_t local_ep,
                         int64_t steps_per_epoch, int32_t drop_last,
                         int64_t seed, int64_t round_idx,
                         const int64_t *worker_ids, int32_t *idx_out,
                         float *w_out) {
  if (!index_matrix || !idx_out || !w_out) return 1;
  if (num_workers <= 0 || row_len <= 0 || batch <= 0 || local_ep <= 0 ||
      steps_per_epoch <= 0)
    return 2;
  const int64_t padded = steps_per_epoch * batch;
  if (drop_last && padded > row_len) return 3;
  if (!drop_last && (padded < row_len || padded - batch >= row_len)) return 4;

  const int64_t ep_stride = padded;                 // per-epoch output elems
  const int64_t worker_stride = local_ep * padded;  // per-worker output elems

  int32_t *perm = new int32_t[row_len];
  for (int64_t wi = 0; wi < num_workers; ++wi) {
    const int32_t *row = index_matrix + wi * row_len;
    const int64_t wid = worker_ids ? worker_ids[wi] : wi;
    for (int64_t ep = 0; ep < local_ep; ++ep) {
      Xoshiro256ss rng(mix_key(seed, round_idx, ep, wid));
      std::memcpy(perm, row, sizeof(int32_t) * (size_t)row_len);
      // Fisher-Yates over the copied row.
      for (int64_t i = row_len - 1; i > 0; --i) {
        int64_t j = (int64_t)rng.bounded((uint64_t)(i + 1));
        int32_t t = perm[i];
        perm[i] = perm[j];
        perm[j] = t;
      }
      int32_t *out = idx_out + wi * worker_stride + ep * ep_stride;
      float *wout = w_out + wi * worker_stride + ep * ep_stride;
      for (int64_t k = 0; k < padded; ++k) {
        if (k < row_len) {
          out[k] = perm[k];
          wout[k] = 1.0f;
        } else {  // wraparound padding, masked out of the math
          out[k] = perm[k - row_len];
          wout[k] = 0.0f;
        }
      }
    }
  }
  delete[] perm;
  return 0;
}

// Library version tag so the Python side can detect stale cached builds.
int dopt_native_abi_version() { return 2; }

}  // extern "C"
