"""Native (C++) host-runtime components, bound via ctypes.

The reference is pure Python (SURVEY §2: no native components anywhere
in the tree), so nothing here is owed for parity — this is the
framework's own host runtime: per-round batch-plan generation in C++
(``plan.cpp``) so the host side never throttles the TPU at large worker
counts.

Build model: compiled lazily with ``g++ -O3 -shared -fPIC`` into the
package directory on first use and cached (mtime-checked against the
source); every entry point degrades gracefully to the numpy
implementation when no compiler or binary is available, so the native
layer is a pure accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "plan.cpp")
_ABI_VERSION = 2
# ABI version in the filename: a cached .so from a different source
# generation gets a different name, so a rebuild can never collide with
# an already-dlopened stale handle (glibc returns the existing handle
# for a known pathname).
_LIB = os.path.join(_DIR, f"libdopt_host_v{_ABI_VERSION}.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    """Compile plan.cpp → libdopt_host.so. Returns success."""
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        fresh = os.path.exists(_LIB) and (
            not os.path.exists(_SRC)
            or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        )
        if not fresh and not _build():
            return None
        try:
            # Single dlopen, then validate; never re-dlopen the same
            # pathname in-process (it would return the stale handle).
            lib = ctypes.CDLL(_LIB)
            lib.dopt_native_abi_version.restype = ctypes.c_int
            if lib.dopt_native_abi_version() != _ABI_VERSION:
                return None  # pathological stale build → numpy fallback
            lib.dopt_fill_batch_plan.restype = ctypes.c_int
            lib.dopt_fill_batch_plan.argtypes = [
                ctypes.POINTER(ctypes.c_int32),  # index_matrix
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # W, L, B
                ctypes.c_int64, ctypes.c_int64,  # local_ep, steps_per_epoch
                ctypes.c_int32,                  # drop_last
                ctypes.c_int64, ctypes.c_int64,  # seed, round_idx
                ctypes.POINTER(ctypes.c_int64),  # worker_ids (nullable)
                ctypes.POINTER(ctypes.c_int32),  # idx_out
                ctypes.POINTER(ctypes.c_float),  # w_out
            ]
            _lib = lib
        except (OSError, AttributeError):
            # unloadable binary / missing symbol → graceful numpy fallback
            _lib = None
        return _lib


def native_available() -> bool:
    return load_native() is not None


def fill_batch_plan_native(
    index_matrix: np.ndarray,
    *,
    batch_size: int,
    local_ep: int,
    seed: int,
    round_idx: int,
    drop_last: bool = False,
    worker_ids: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Native batch-plan fill; returns (idx, weight) arrays shaped like
    ``dopt.data.pipeline.make_batch_plan``'s, or None when the native
    library is unavailable (caller falls back to numpy).

    ``worker_ids`` maps each row of ``index_matrix`` to its true worker
    id for RNG keying (compact-sampling: pass the m sampled rows plus
    their ids and get plans bit-identical to those rows of the full
    plan).  None means row i is worker i.

    Deterministic in (seed, round_idx, epoch, worker) via a seeded
    xoshiro256** stream — NOT bit-identical to the numpy PCG64 plans
    (use the numpy path for torch-oracle parity runs).
    """
    lib = load_native()
    if lib is None:
        return None
    im = np.ascontiguousarray(index_matrix, dtype=np.int32)
    w, l = im.shape
    bs = min(batch_size, l)
    steps_per_epoch = (l // bs) if drop_last else -(-l // bs)
    s = local_ep * steps_per_epoch
    idx = np.empty((w, s, bs), dtype=np.int32)
    weight = np.empty((w, s, bs), dtype=np.float32)
    if worker_ids is not None:
        wid = np.ascontiguousarray(worker_ids, dtype=np.int64)
        if wid.shape != (w,):
            raise ValueError(f"worker_ids shape {wid.shape} != ({w},)")
        wid_ptr = wid.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    else:
        wid_ptr = None
    rc = lib.dopt_fill_batch_plan(
        im.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        w, l, bs, local_ep, steps_per_epoch, int(drop_last),
        seed, round_idx, wid_ptr,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        weight.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if rc != 0:
        return None
    return idx, weight


__all__ = ["load_native", "native_available", "fill_batch_plan_native"]
