"""Typed, frozen experiment configuration.

The reference drives everything through a ``DotDict`` built from a Colab
form cell (``Decentralized Optimization/src/utils.py:14-27`` and the
notebook config cells); missing keys silently read as ``None`` and
several orchestrators mutate the shared args object
(``Distributed Optimization/src/simulators.py:171-180``).  ``dopt``
replaces that with frozen dataclasses while keeping the reference's
parameter *names* (num_users, frac, local_ep, local_bs, lr, momentum,
rho, topology, mode, shards, iid, seed) so every published experiment
config maps 1:1.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Mapping


@dataclass(frozen=True)
class DataConfig:
    """Dataset selection + partitioning (reference ``get_dataset`` args)."""

    dataset: str = "mnist"  # mnist | fmnist | cifar10 | cifar100 | synthetic | a9a
    iid: bool = True
    shards: int = 2          # non-IID shards per user (P2 sampling.py:11-28)
    num_users: int = 8
    data_dir: str | None = None   # directory with raw files; None -> auto/synthetic
    synthetic_train_size: int = 2048
    synthetic_test_size: int = 512
    # (no 'unequal' knob: the reference has no unequal split — P1's
    # hardcoded shard tables and P2's args.shards are both equal-size —
    # so the field would be a silent no-op; partitioners reject what
    # they cannot honour instead.)
    plan_impl: str = "numpy"  # "native" = C++ host runtime (dopt.native)
    # for per-round batch-plan generation; numpy remains the
    # torch-oracle-parity mode
    local_holdout: float = 0.0
    # Fraction of each worker's shard held out as LOCAL validation, the
    # reference's ``train_val_test`` split: ``val_size = max(int(L/10), 1)``
    # and training runs on the remaining samples only (P1 clients.py:25-28,
    # P2 clients.py:20-22).  0.1 reproduces the reference; 0.0 (default)
    # trains on the full shard (the idiomatic mode).  When enabled the
    # engines also emit per-epoch per-worker
    # {train_loss, train_acc, val_acc, val_loss} rows (clients.py:45-50)
    # into ``trainer.client_history``.
    holdout_mode: str = "deterministic"
    # deterministic — val = FIRST val_size indices of the worker's shard
    #                 (P1, clients.py:26-28).
    # random        — seeded random choice without replacement
    #                 (P2, clients.py:21-22).


@dataclass(frozen=True)
class ModelConfig:
    """Model zoo selection (reference ``args.model`` string dispatch)."""

    model: str = "model1"    # model1 | model3 | mlp | resnet18 | logistic
    stage_sizes: tuple[int, ...] | None = None
    # resnet18 only: residual blocks per stage (None = the standard
    # (2, 2, 2, 2)).  Smaller values give shallow variants for tests
    # and the multichip dryrun, where a full-depth compile on one CPU
    # core would blow the time budget.
    faithful: bool = True
    # faithful=True reproduces the reference's Softmax-head +
    # CrossEntropyLoss double-softmax (models.py:22-27 + clients.py:11);
    # False uses the corrected logits head.
    num_classes: int = 10
    input_shape: tuple[int, ...] = (28, 28, 1)   # NHWC (TPU-native layout)
    param_dtype: str = "float32"
    # Storage dtype of the worker-stacked training state (params,
    # momentum, duals/controls): "bfloat16" halves HBM for the [W, ...]
    # stacked tree and the bytes every consensus/aggregation collective
    # moves, at a numerics cost (the update itself then rounds to bf16
    # each step).  float32 is the oracle-parity mode.
    compute_dtype: str = "float32"   # "bfloat16" for the fast path
    stacked_impl: str = "auto"
    # How the engines execute the per-worker forward over the [W, ...]
    # stacked state: "auto" uses the grouped-conv stacked program where
    # one exists (model1/model3 — dopt.models.make_stacked_apply; ~3×
    # faster than the vmap on TPU, identical math up to float
    # reassociation inside the conv), "vmap" forces the vmapped
    # per-worker path (the bit-level oracle-parity mode).


@dataclass(frozen=True)
class OptimizerConfig:
    """Local SGD settings (reference ``clients.py`` optimizer construction)."""

    optimizer: str = "sgd"
    # Only 'sgd' exists (the reference's single optimizer,
    # clients.py:14); anything else is rejected loudly at trainer
    # construction rather than silently running SGD.
    lr: float = 0.01
    momentum: float = 0.5
    weight_decay: float = 0.0
    # ℓ2 coefficient added to the local loss (λ‖θ‖²/2, as an explicit
    # loss term rather than torch-style decoupled decay so FedProx/ADMM
    # gradient edits compose with it identically on both backends).
    rho: float = 0.1   # FedProx proximal weight / FedADMM penalty
    clip_norm: float = 0.0
    # Per-worker global-norm gradient clip applied to the final gradient
    # (after any FedProx/ADMM/SCAFFOLD edit), 0 = off.  Off by default:
    # the reference has no clipping and the faithful oracle contract
    # pins its exact update.  The corrected-head (faithful=False) CNNs
    # need it in bf16 — raw-logit CE on the un-normalised reference
    # architecture sits at the edge of stability at the reference lr,
    # and bf16 gradient rounding tips runs across it (measured
    # run-to-run final-acc scatter 0.3–0.97; clip 1.0 removes it —
    # results/bench_idiomatic.json).
    fused_update: bool = False  # pallas single-pass momentum-SGD update
    # (dopt.ops.fused_update); numerics identical to the jnp path


@dataclass(frozen=True)
class FederatedConfig:
    """Server-coordinated path (reference P1 ``servers.py``)."""

    algorithm: str = "fedavg"   # fedavg | fedprox | fedadmm | scaffold
    frac: float = 0.1           # fraction of users sampled per round
    rounds: int = 20
    local_ep: int = 10
    local_bs: int = 50
    compact: bool | None = None
    # Compact-sampling fast path: gather the m sampled workers' state
    # into [m, ...] lanes, train only those, scatter back — instead of
    # training all N lanes and mask-discarding (the faithful wart).
    # None = auto (on for a single-device mesh when frac < 1); numerics
    # match the full-width path up to float summation order.
    block_rounds: int = 1
    # >1 fuses that many rounds into one lax.scan jit dispatch (same
    # math, same per-round eval cadence) — the dispatch-overhead killer
    # for small models; mirrors GossipConfig.block_rounds.
    comm_dtype: str | None = None
    # Wire-only compression of the aggregation reduce (full-width path
    # on a sharded mesh): per-device partial sums cross ICI/DCN at this
    # dtype (e.g. "bfloat16"); local math stays full precision.
    # Mirrors GossipConfig.comm_dtype.
    staleness_max: int = 0
    # Staleness-aware aggregation (0 = off, the hard-drop reference
    # semantics).  When > 0, a deadline-missed straggler
    # (``FaultConfig.straggler_policy="drop"``) or a delay-faulted
    # uplink (``FaultConfig.msg_delay``) is no longer discarded: the
    # client finishes its full local work, its update is buffered, and
    # it is admitted into the aggregate of round t+d (d <=
    # staleness_max; later arrivals are dropped) with weight
    # ``staleness_decay**d`` — so late work still moves theta, just
    # with discounted trust.  Admitted updates pass the same non-finite
    # screen as immediate ones and respect quarantine, composing with
    # the Byzantine path.  Forces full-width per-round execution;
    # fedavg/fedprox only (SCAFFOLD/ADMM companion state has no
    # late-admission semantics).
    staleness_decay: float = 0.5
    # Per-round decay of a buffered update's aggregation weight: an
    # update admitted d rounds late enters the weighted average with
    # weight decay**d (1.0 = late counts like fresh, small = distrust
    # stale work).
    update_sharding: str = "off"
    # "off" | "scatter".  "scatter" runs the aggregation/weight-update
    # hot path sharded (Xu et al., arXiv:2004.13336): the parameter
    # tree is flattened into size-bounded buckets
    # (``update_bucket_mb``), each device reduce-scatters its masked
    # partial sums so it owns only a 1/D shard of the flat sum, the
    # aggregation update (the divide) runs on that shard, and one
    # all-gather re-forms the replicated theta — instead of every
    # device redundantly computing the full |θ| average.  Per-bucket
    # collectives overlap with compute under the XLA latency-hiding
    # scheduler (dopt.parallel.mesh.enable_latency_hiding_scheduler).
    # "off" compiles the exact pre-change program (bit-identical).
    # Requires aggregator='mean', no comm_dtype/staleness/compact, and
    # a flat 1-D mesh; numerics match the dense path to f32 summation
    # order (allclose, not bit-equal), and scatter-vs-scatter runs are
    # bit-reproducible and resume-exact.
    update_bucket_mb: float = 4.0
    # Scatter-mode bucket size bound (per-worker payload MB per
    # bucket): small enough that several collectives are in flight for
    # the scheduler to overlap, large enough to amortise collective
    # launch overhead.
    fused_update: str = "off"
    # "off" | "on".  "on" restructures the full-width round carry so
    # the aggregation epilogue (masked average of the survivors' new
    # params) runs as ONE fused Pallas pass over the flat-bucket
    # UpdateShardSpec layout (``dopt.ops.fused_mix_update``): the carry
    # holds theta BROADCAST over the worker axis, each round contracts
    # the masked per-lane displacements (p_i − theta) with the
    # mean-weight matrix and adds theta back in the same HBM pass —
    # equal to the jnp masked_average path to f32 summation order
    # (allclose, not bit-equal), and fused-vs-fused runs are
    # bit-reproducible, blocked-exact and resume-exact.  "off" (the
    # default) compiles the exact pre-change programs (fingerprint-
    # gated, bit-identical).  fedavg/fedprox full-width mean only:
    # rejected (loudly) with scaffold/fedadmm, staleness-aware
    # aggregation, robust aggregators, clip_radius, corrupt faults,
    # compact gather, update_sharding='scatter', comm_dtype,
    # population mode, and multi-device meshes.
    prefetch: str = "off"
    # "off" | "on".  "on" overlaps the host pipeline with device
    # compute on the blocked/chaos-blocked/population run loops: block
    # b+1's batch plans are built and staged to device
    # (``dopt.data.prefetch.PrefetchStager``) while block b runs —
    # dispatch → stage-next → fetch instead of build → dispatch →
    # fetch.  Stateful host draws (the client-sampling stream) stay on
    # the main thread in block order and the post-fetch ledger replay
    # consumes the drawn inputs, so prefetch-on runs are BIT-IDENTICAL
    # to prefetch-off (History, fault ledger, telemetry canonical
    # stream), and staging never crosses a checkpoint boundary so
    # kill-and-resume stays exact.  "off" (the default — the
    # oracle-parity mode) runs the exact pre-change host loop.
    # Rejected for population mode with client-keyed quarantine armed
    # (next round's eligibility depends on this round's screen
    # feedback, which only exists after the fetch).
    diagnostics: str = "off"
    # "off" | "on".  "on" computes per-round convergence diagnostics
    # INSIDE the compiled round (global update/gradient/parameter L2
    # norms, per-lane train-loss mean + max-min spread, and the fleet
    # lane-dispersion mean_i ||p_i - theta||), threads them through the
    # blocked lax.scan as extra packed outputs, and emits them as
    # deterministic ``gauge`` telemetry at the post-fetch boundary —
    # per-round, fused-blocked, prefetched and killed-and-resumed runs
    # produce canonically identical diagnostic streams (dopt.obs).
    # Also arms the non-deterministic device-resource channel
    # (``resource`` HBM samples per block, ``compile`` retrace events)
    # when telemetry is attached.  "off" (default) compiles the exact
    # pre-change programs and runs the exact pre-change host loop.
    # Rejected for population mode (stateless wave clients carry no
    # lane momentum/params to diagnose).


@dataclass(frozen=True)
class GossipConfig:
    """Serverless gossip/consensus path (reference P2 ``simulators.py``)."""

    algorithm: str = "dsgd"     # dsgd | nocons | centralized | fedlcon | gossip | choco
    topology: str = "circle"    # circle | star | complete | dynamic | random
    #                           # | torus | hierarchical | one_peer_exp
    # 'one_peer_exp' is the one-peer time-varying exponential schedule
    # (arXiv:2410.11998): round t mixes every worker with exactly ONE
    # peer at shift 2^(t mod log2 n), W_t = (I + P_{2^t})/2 with exact
    # dyadic weights (power-of-2 worker counts only).  The schedule is
    # stateless per round (pure function of t, like FaultPlan draws) so
    # it is bit-reproducible, blocked-exact and resume-exact, and its
    # shift union {0, 1, 2, ..., n/2} rides the sharded circulant
    # ppermute path (comm_impl='shift'/'auto') — O(lanes·|θ|) bytes per
    # round instead of the dense all-gather.
    mode: str = "stochastic"    # stochastic | double_stochastic | metropolis | uniform | ones
    rounds: int = 10
    local_ep: int = 4
    local_bs: int = 128
    eps: int = 1                # consensus sweeps per round (FedLCon)
    eval_mode: str = "full"     # full | sharded
    # How the per-round fleet eval reads the test set.  'full' is the
    # reference's semantics (EVERY client evaluates the ENTIRE test
    # split, P2 clients.py:71-86) — W·|test| sample-forwards per eval,
    # which on baseline5 costs more device time than the training round
    # itself (3.1 of 5.5 s/round measured).  'sharded' gives each
    # worker a round-robin 1/W shard: the fleet-MEAN metric is an
    # unbiased estimate from |test| total forwards, per-worker rows are
    # ~W× noisier.  Throughput trims use 'sharded'; parity runs keep
    # 'full'.
    mixing: str = "sync"        # consensus timing: sync | async
    # 'sync' (default) is the bulk-synchronous mix: round t's consensus
    # reads round t's neighbor state — the exact pre-change program.
    # 'async' is staleness-1 overlapped gossip (the communication/
    # compute overlap of arXiv:2410.11998 / D-PSGD practice): round t
    # mixes x_i <- W_ii·x_i(t) + Σ_{j≠i} W_ij·x_j(t-1), consuming the
    # PREVIOUS round's neighbor state via a double-buffered carry in
    # the blocked lax.scan — round r's neighbor communication fully
    # overlaps round r+1's local compute, and a late peer's stale
    # shard never stalls the round.  Round 0 mixes the shared init, so
    # async round 0 ≡ sync round 0.  The prev buffer is scan carry +
    # a checkpoint array ("async_prev"), keeping async runs
    # bit-reproducible, blocked-exact and resume-exact; crash/churn
    # repair applies to the FULL matrix before the diag/off-diag
    # split, so a departed worker's lanes degrade to self-weight
    # (identity row → pure local step) instead of blocking the mix.
    # dsgd-only; rejected with the robust layer, link faults/push_sum,
    # eps sweeps, update_sharding='scatter' and population mode.
    comm_impl: str = "auto"     # consensus collective: auto | dense | shift
    # 'dense'  — all_gather + contraction with the [n, n] mixing matrix
    #            (right for complete/random/arbitrary graphs).
    # 'shift'  — lax.ppermute over ICI: the [n, n] circulant decomposes
    #            into device-level ring rotations + a static lane slice
    #            (workers fold onto devices in n/D lanes), moving
    #            O(rotations·lanes·|θ|) bytes/round instead of the dense
    #            O(n·|θ|).  Requires a flat 1-D mesh and a topology
    #            whose schedule decomposes into circulant shifts.
    # 'auto'   — shift when those conditions hold and the ppermute bytes
    #            beat the all_gather with a 2× margin; dense otherwise.
    # Determinism note: runs are bit-reproducible for a fixed config AND
    # mesh, but 'auto' picks per mesh shape, and the two paths can
    # differ in the last float bit for non-dyadic weights (gemm FMA vs
    # mul+add); pin 'dense' or 'shift' for cross-hardware bit-replay.
    block_rounds: int = 1       # rounds fused into ONE jit (lax.scan) per
    # dispatch; >1 removes per-round host sync + dispatch overhead (the
    # fast path for throughput; eval happens at block boundaries only)
    faithful_bugs: bool = False
    # faithful_bugs=True replicates documented reference bugs (FedLCon's
    # stale new_weights accumulation, simulators.py:189-196) for oracle
    # comparison; the idiomatic path fixes them.
    self_weight: bool = False   # reference mixing has zero diagonal (SURVEY §6.2)
    hier_groups: int = 2        # topology='hierarchical': group count
    hier_period: int = 4        # ... global (cross-DCN) mix every N rounds
    choco_gamma: float = 1.0    # CHOCO-SGD consensus step size γ
    # CHOCO theory wants γ scaled DOWN with the compressor's contraction
    # factor δ (γ ≈ δ·spectral-gap terms); γ=1 is only safe because
    # compression_ratio defaults to 1 (identity → exact D-SGD).  With a
    # real compressor (ratio < 1 or qsgd) keep γ well below 1 — e.g.
    # γ≈0.1·ratio — or the consensus step can diverge; the trainer warns
    # on the risky combination.
    compression: str = "topk"   # CHOCO compressor: topk | randk | qsgd | none
    compression_ratio: float = 1.0
    # topk/randk: fraction of entries communicated (ratio=1 = identity;
    # with γ=1 that reduces exactly to D-SGD — tested; randk keeps a
    # FIXED k = ceil(ratio·n) index set per round, so wire size is
    # constant).  qsgd: ratio sets the quantization level count
    # (ratio=1 → 256 levels, not the identity — use compression='none'
    # for the exact reduction), unless qsgd_levels overrides it.
    # algorithm='choco' (Koloskova et al. 2019): workers gossip a
    # COMPRESSED difference Q(x_i − x̂_i) with error feedback, then take
    # the consensus step x_i += γ·((W x̂)_i − x̂_i).
    qsgd_levels: int = 0
    # Explicit QSGD level count (e.g. 16 = 4-bit range); 0 derives the
    # count from compression_ratio (ratio·256).  Separate knob so the
    # quantizer is not configured through the sparsifiers' fraction
    # semantics; only valid with compression='qsgd'.
    comm_dtype: str | None = None
    # Communication compression for the consensus collective: e.g.
    # "bfloat16" narrows model shards BEFORE the cross-worker
    # contraction/ppermute, halving ICI/DCN bytes per gossip round;
    # params and local compute stay at their own dtype.  None =
    # communicate at the compute dtype.
    #
    # Determinism note: with comm_dtype set, the two comm_impl paths are
    # NOT bit-identical — the dense path narrows every gathered lane,
    # while the shift path keeps locally-sourced lanes (shift 0 and the
    # q==0 parts of shifts that straddle a device's lane fold) exact.
    # Compressed-mode results therefore depend on comm_impl AND on the
    # mesh shape / lane fold (workers-per-device).  Exact-dtype runs
    # (comm_dtype=None) are bit-identical across both paths and any
    # fold — that equality is what the test suite pins.
    correction: str = "none"
    # Gossip bias correction under asymmetric message loss: "none" runs
    # the plain consensus (receiver rows renormalised after drops — the
    # effective matrix is then no longer doubly stochastic and the fleet
    # converges to a BIASED weighted average), "push_sum" runs push-sum /
    # ratio consensus (Kempe et al.; Stochastic Gradient Push, Assran et
    # al. 2019): every worker carries a scalar mass weight alongside its
    # parameters, both travel through the SAME column-stochastic
    # (mass-conserving) effective matrix, and the de-biased estimate is
    # params/mass — exact-mean consensus under arbitrary drop/delay
    # traces.  "push_sum" forces the dense comm path and per-round
    # execution; with no link faults and a doubly-stochastic schedule
    # the mass stays exactly 1.0 (divide/multiply by 1.0 is exact).
    update_sharding: str = "off"
    # "off" | "scatter".  "scatter" runs the consensus mix on a 1/D
    # shard of the FLATTENED parameter tree (arXiv:2004.13336 applied
    # to gossip): the tree is bucketed into size-bounded [W, Fb] slabs
    # (``update_bucket_mb``), the dense mix becomes per-device partial
    # contraction + ``psum_scatter`` (no device ever materialises the
    # [n, |θ|] gathered fleet state), the ppermute/shift schedule runs
    # as a sharded circulant contraction over the same flat buckets,
    # and the per-bucket collectives overlap with compute under the
    # XLA latency-hiding scheduler.  "off" compiles the exact
    # pre-change program (bit-identical).  Eligible for dsgd/fedlcon/
    # gossip with crash/straggler/partition/churn faults and blocked
    # execution; rejected (loudly) with the robust layer, link faults/
    # push-sum, choco, comm_dtype, and hybrid meshes.  Numerics: f32
    # trees agree with the dense path to summation order (the
    # allclose-pinned contract); bf16 trees additionally keep the
    # mixing matrix + accumulation in f32 where the dense path
    # contracts at bf16 — strictly more precise, but a larger delta vs
    # dense.  Scatter-vs-scatter is bit-reproducible and resume-exact.
    update_bucket_mb: float = 4.0
    # Scatter-mode bucket size bound (per-worker payload MB per
    # bucket); see FederatedConfig.update_bucket_mb.
    fused_update: str = "off"
    # "off" | "on".  "on" restructures the gossip scan carry into
    # (post-mix params, displacement buffer) so the round's consensus
    # epilogue runs as ONE fused Pallas pass over the flat-bucket
    # UpdateShardSpec layout (``dopt.ops.fused_mix_update``): the mix
    # contracts the PREVIOUS round's pre-update params with W and
    # applies the buffered local displacement in the same HBM pass
    # (q_t = W·q_{t-1} − fbuf, fbuf = q_{t-1} − p'_{t-1}).  This is
    # the D-PSGD update ordering (Lian et al., arXiv:1705.09056: the
    # local displacement is applied UNMIXED after the contraction) — a
    # documented variant of the default mix-then-step trajectory, NOT
    # bit-equal to it; the fused trajectory is pinned f32-allclose to
    # its own jnp reference (``dopt.ops.mix_sgd_reference``) and
    # fused-vs-fused runs are bit-reproducible, blocked-exact and
    # resume-exact (the displacement buffer rides the scan carry and
    # the checkpoint as "fused_buf").  "off" (the default) compiles
    # the exact pre-change programs (fingerprint-gated,
    # bit-identical).  dsgd/gossip dense single-sweep consensus only:
    # rejected (loudly) with the robust layer, link faults/push-sum,
    # mixing='async', choco, fedlcon eps sweeps, nocons/centralized,
    # update_sharding='scatter', comm_dtype, comm_impl='shift',
    # population mode, and multi-device meshes.
    prefetch: str = "off"
    # "off" | "on".  "on" overlaps the host pipeline with device
    # compute on the blocked run loops (clean, link-mode and
    # fused-quarantine): block b+1's batch plans + stacked
    # fault/link/corrupt inputs are built and staged to device while
    # block b runs (``dopt.data.prefetch.PrefetchStager``).  Stateful
    # draws (the 'gossip' matching-matrix stream) stay on the main
    # thread in block order and the post-fetch ledger replay reuses
    # the drawn inputs, so prefetch-on runs are BIT-IDENTICAL to
    # prefetch-off (History, fault ledger, telemetry canonical
    # stream); staging never crosses a checkpoint boundary, keeping
    # kill-and-resume exact.  "off" (the default — the oracle-parity
    # mode) runs the exact pre-change host loop.  Rejected in
    # population mode (the gossip cohort binding mutates the registry
    # and appends its ledger row at plan time — the federated engine
    # is the prefetch-eligible population path).
    diagnostics: str = "off"
    # "off" | "on".  "on" computes per-round convergence diagnostics
    # INSIDE the compiled round (global update/gradient/parameter L2
    # norms, per-lane train-loss mean + max-min spread, and the TRUE
    # per-round consensus distance mean_i ||p_i - p_bar||), threads
    # them through the blocked lax.scan as extra packed outputs, and
    # emits them as deterministic ``gauge`` telemetry at the post-fetch
    # boundary — per-round, fused-blocked, prefetched and
    # killed-and-resumed runs produce canonically identical diagnostic
    # streams (dopt.obs).  Also arms the non-deterministic
    # device-resource channel (``resource`` HBM samples per block,
    # ``compile`` retrace events) when telemetry is attached.  "off"
    # (default) compiles the exact pre-change programs and runs the
    # exact pre-change host loop.
    dropout: float = 0.0
    # DEPRECATED back-compat alias for FaultConfig(crash=p) — warns at
    # trainer construction and produces the identical fault trace
    # (dopt.faults.FaultPlan synthesizes the config); set
    # ExperimentConfig.faults instead.  Scheduled for REMOVAL in release
    # 0.2.0.  Per-round probability each worker is down: down workers
    # skip consensus AND local training, the mixing matrix is repaired
    # (dopt.topology.repair_for_dropout — the degenerate all-links-down
    # case of the per-edge link-fault model, see FaultConfig.msg_drop)
    # and they rejoin with stale params.


@dataclass(frozen=True)
class FaultConfig:
    """Deterministic fault injection (``dopt.faults.FaultPlan``).

    The reference assumes every simulated worker is alive and instant
    (SURVEY §5); real decentralized systems treat crashes, stragglers
    and partitions as the steady state.  All draws are keyed by
    (seed, round) — stateless — so the same config replays the same
    fault trace, per-round and blocked execution inject identical
    faults, and a killed-and-resumed run sees exactly the faults a
    continuous run would.  Every injected fault lands in the run's
    fault ledger (``History.faults``)."""

    crash: float = 0.0
    # Per-round per-worker crash probability.  A crashed worker is down
    # for the round: it skips consensus and local training (gossip) or
    # contributes nothing to the server aggregate (federated) and
    # rejoins next round with stale-but-valid state.
    straggle: float = 0.0
    # Per-round per-worker straggler probability (crashes win ties).
    straggle_frac: float = 0.5
    # Fraction of its local work a straggler finishes before the round
    # deadline: epochs under the holdout's epoch loop, SGD steps on the
    # flat path (ceil(frac * total), so frac > 0 always does some work).
    straggler_policy: str = "partial"
    # Federated only: 'partial' aggregates the straggler's truncated
    # update; 'drop' removes it from the round (FedAvg-paper server
    # deadline) — combine with over_select so the aggregate still
    # averages ~m clients.  Gossip has no server deadline and always
    # applies 'partial'.
    over_select: float = 0.0
    # Federated: sample ceil(m·(1+over_select)) clients, keep the first
    # m survivors after crashes/deadline drops (surplus is released and
    # ledgered) — the FedAvg-paper over-selection pattern.
    partition: float = 0.0
    # Per-round probability a network partition STARTS; while active,
    # the fleet is split into partition_groups random groups.  Gossip:
    # cross-group mixing edges are cut (matrix repaired as data,
    # ``repair_for_partition``).  Federated: only group 0 can reach the
    # server; other groups are unreachable for the span.
    partition_span: int = 2     # rounds a partition lasts once started
    partition_groups: int = 2   # number of sides of the cut
    corrupt: float = 0.0
    # Per-round per-worker probability the worker LIES: its contributed
    # update (federated) / the state it broadcasts to neighbors (gossip)
    # is replaced by a corrupted value before aggregation — the
    # Byzantine threat model, vs. crash's fail-stop model.  Crashes win
    # ties (a down worker sends nothing).  Injection happens INSIDE the
    # jitted round functions (``dopt.faults.corrupt_update``) from the
    # same stateless per-round streams, so corrupted runs stay
    # bit-reproducible, blocked-execution-exact and resume-exact.
    corrupt_mode: str = "nan"
    # What the lie looks like: 'nan' | 'inf' (non-finite poison),
    # 'scale' (norm blow-up by corrupt_scale), 'signflip' (update
    # negated through the reference point), 'stale' (replay of the
    # worker's previous update; federated engine only — gossip carries
    # no per-worker previous-send state).
    corrupt_scale: float = 100.0   # blow-up factor for mode='scale'
    corrupt_max: int = 0
    # Cap on corrupted workers per round (0 = no cap).  The cap keeps
    # the LOWEST-INDEXED workers among the round's draws, so
    # ``corrupt=1.0, corrupt_max=f`` pins workers 0..f-1 as PERSISTENT
    # adversaries — the classic fixed-f Byzantine setting robust
    # aggregators state their breakdown points against.
    msg_drop: float = 0.0
    # Per-round per-DIRECTED-EDGE message-loss probability (the lossy-
    # link model).  Each direction of each link draws independently, so
    # loss is asymmetric in general — which is exactly what makes the
    # row-renormalised effective mixing matrix non-doubly-stochastic
    # and plain gossip converge to a biased average (the push-sum
    # correction, ``GossipConfig.correction="push_sum"``, recovers the
    # true mean).  Gossip: the edge is cut for the round and the
    # surviving weights repaired as data.  Federated: the probability a
    # sampled client's UPLINK to the server loses the round's update
    # (the client keeps its local state; the server sees a failure).
    msg_delay: float = 0.0
    # Per-round per-directed-edge message-DELAY probability.  A delayed
    # gossip edge delivers the sender's state d rounds late (d drawn
    # uniformly in 1..msg_delay_max), so the receiver mixes against a
    # stale value — the bounded-staleness asynchronous-gossip model.
    # The staleness buffer is engine state, carried through blocked
    # execution and checkpoints.  Federated: a sampled client's uplink
    # update arrives d rounds late; with
    # ``FederatedConfig.staleness_max`` > 0 it is buffered and admitted
    # with decay weighting, otherwise it is lost like a drop.
    msg_delay_max: int = 2
    # Maximum delay D in rounds (the staleness bound; buffer depth is
    # compiled from it, so keep it small).
    churn: float = 0.0
    # Per-round per-worker probability an elastic-membership LEAVE event
    # starts: the worker departs the fleet for ``churn_span`` rounds and
    # then rejoins (the join event) with its stale state.  While away
    # the mixing matrix is repaired around it (identity row — same
    # healing as a crash) / it is excluded from federated sampling, and
    # its data shard is deterministically reassigned to the next alive
    # worker (``dopt.data.partition.reassign_shards``) so the departed
    # data keeps being trained on.  Draws are stateless per round like
    # every other fault kind.
    churn_span: int = 4         # rounds a departed worker stays away
    seed: int | None = None     # fault-stream seed; None = experiment seed


@dataclass(frozen=True)
class RobustConfig:
    """Byzantine-robust aggregation & quarantine (``dopt.robust``).

    The defense side of the threat model: ``FaultConfig.corrupt``
    injects lies, this config decides what the aggregation layer does
    about them.  ``None`` (or all defaults) keeps the exact masked-mean
    programs — clean runs stay bit-identical."""

    aggregator: str = "mean"
    # Federated server aggregation over the round's surviving updates:
    # 'mean' (the reference masked average, breakdown point 0),
    # 'trimmed_mean' (coordinate-wise, tolerates < trim_frac·n liars),
    # 'median' (coordinate-wise, breakdown 1/2), 'krum' / 'multi_krum'
    # (distance-based selection, tolerates f with n > 2f + 2).
    # All are jittable pure functions of (stacked updates, mask).
    trim_frac: float = 0.1
    # trimmed_mean: fraction trimmed from EACH end per coordinate
    # (k = floor(trim_frac · n_alive), clamped so >= 1 value survives).
    krum_f: int = 1
    # krum/multi_krum: assumed number of Byzantine workers f; each
    # worker is scored by its n_alive − f − 2 closest neighbors.
    multi_krum_m: int = 0
    # multi_krum: average the m best-scored workers (0 = auto:
    # n_alive − krum_f).  krum is multi_krum with m = 1.
    clip_radius: float = 0.0
    # Norm clip (0 = off).  Federated: worker updates are clipped to an
    # L2 ball of this radius around theta before aggregation.  Gossip:
    # the clipped-gossip rule — each worker clips every neighbor
    # DEVIATION ``x_j − x_i`` to this radius before applying the mixing
    # weights, so one liar moves any honest worker at most
    # W_ij·clip_radius per round (composes with partition/crash repair,
    # which act on the matrix itself).
    quarantine_after: int = 0
    # Detection/quarantine layer (0 = off): a worker whose update is
    # screened (non-finite, or majority-clipped in gossip) this many
    # rounds IN A ROW is quarantined — masked out via the engines'
    # existing alive/participation machinery and recorded in the fault
    # ledger — then readmitted after ``quarantine_rounds``.
    quarantine_rounds: int = 8  # backoff length before readmission


@dataclass(frozen=True)
class PopulationConfig:
    """Client population registry (``dopt.population``).

    Decouples the client POPULATION (1k–10k host-side client records)
    from the fixed-width device LANES: each round a seeded, stateless
    cohort sampler draws ``cohort`` clients from the eligible
    population, the cohort is bound onto the existing validity-masked
    lanes in ``ceil(cohort / lanes)`` waves, per-device partial
    weighted sums accumulate across the waves, and ONE cross-device
    bucketed reduce (the ``masked_average_scatter`` flat-tree path)
    forms the round's aggregate — so cohort size scales past what the
    lane width (or device memory) can hold in one pass.  Per-client
    state (shard assignment, participation counts, staleness,
    quarantine streaks) lives in host-side arrays keyed by CLIENT id,
    so adversaries and quarantine sentences persist across cohorts.
    ``None`` on ExperimentConfig keeps the exact pre-population
    programs (python-level gating)."""

    clients: int = 1000
    # Population size P: how many client records the registry holds.
    # Clients are stateless FedAvg/FedProx participants (they load
    # theta, train their assigned shard, return an update) — only their
    # registry row persists between the rounds they are sampled in.
    cohort: int = 64
    # Clients sampled per round (M).  When fewer than M clients are
    # eligible (quarantine/churn), the round runs the smaller cohort —
    # cohort size is DATA (lane validity masks), never a shape.
    seed: int | None = None
    # Cohort-sampler seed; None = the experiment seed.  Draws are keyed
    # statelessly by (seed, round), so sampling is bit-reproducible and
    # resume-exact without any persisted RNG state.
    lanes: int | None = None
    # Device lane width per wave (the fixed execution width the cohort
    # is folded onto).  None = ``data.num_users`` (one lane per data
    # shard).  Must divide the device count evenly, like num_users.


@dataclass(frozen=True)
class SeqLMConfig:
    """Sequence-parallel language-model training (``dopt.engine.seqlm``).

    Nothing like it exists in the reference (no attention, no sequence
    axis — SURVEY §2.3); this drives the framework's long-context
    substrate (``dopt.parallel.sequence``) as a real training component:
    a decoder-only TransformerLM with the SEQUENCE axis sharded over the
    mesh and attention running as ring (ppermute KV rotation) or
    Ulysses (all_to_all head resharding) — exact, not approximate."""

    steps: int = 60
    batch: int = 8
    seq_len: int = 512       # divisible by the mesh size
    vocab: int = 64
    dim: int = 128
    depth: int = 2
    heads: int = 4
    attn: str = "ring"       # ring | ulysses | dense (single-device)
    kv_chunk: int = 0
    # ring only: scan each ring block's KV in chunks of this size
    # (flash-style) so per-device score memory is O(block·kv_chunk)
    # instead of O(block²) — the long-sequence memory knob.  0 = whole
    # block at once; must divide seq_len / mesh_size.
    log_every: int = 10


@dataclass(frozen=True)
class CommConfig:
    """Communication substrate schedule (``dopt.parallel.collectives``).

    One knob block shared by BOTH engines: which wire format each flat
    bucket of the ``update_sharding='scatter'`` substrate speaks.  The
    per-bucket schedule (``make_codec_plan``) maps a byte budget onto
    formats — big conv/matmul buckets compress hardest (packed int8 or
    nibble-packed int4 with per-chunk scales and error feedback),
    small norm/bias buckets stay exact — and ``link_byte_budget``
    derives that budget from the lossy-link fault model's goodput.
    ``None`` on ExperimentConfig keeps every pre-change program
    byte-identical (python-level gating)."""

    codec: str = "none"
    # Per-bucket integer codec: "none" | "qsgd" (per-chunk-scaled
    # stochastic int8/int4, dopt.ops.compression.qint_encode).  The
    # gossip engine carries the error-feedback residual as scan state
    # ("comm_residual" in checkpoints); draws are stateless
    # per-(round, bucket, global lane) fold-ins, so compressed runs are
    # bit-reproducible, blocked-exact and resume-exact.
    wire_dtype: str | None = None
    # Dtype narrowing for buckets the codec does NOT cover (and for the
    # whole wire when codec="none"): None | "bfloat16" | "float16".
    byte_budget_mb: float = 0.0
    # Per-lane per-round wire budget in MiB.  0 = no budget: every
    # bucket at least min_codec_bytes large gets the codec at int8.
    # > 0: buckets escalate largest-first (base -> q8 -> q4) until the
    # schedule fits.  Use link_byte_budget(...) to derive it from a
    # FaultConfig's msg_drop/msg_delay rates.
    min_codec_bytes: int = 4096
    # Buckets whose per-lane f32 payload is below this stay at the base
    # wire format — compressing a bias vector saves nothing and costs a
    # scale sidecar.
    chunk: int = 1024
    # Per-lane scale granularity of the integer codec (elements per
    # f32 scale).  Must be even (int4 packs two levels per byte).
    error_feedback: str = "on"
    # "on" | "off": carry the per-bucket quantization residual and fold
    # it back next round (DeepSqueeze/CHOCO error feedback — what keeps
    # aggressive codecs convergent).  "off" drops the residual (an
    # unbiased-codec-only mode for ablations).

    def __post_init__(self) -> None:
        if self.codec not in ("none", "qsgd"):
            raise ValueError(
                f"unknown comm codec {self.codec!r}; one of none|qsgd")
        if self.wire_dtype not in (None, "bfloat16", "float16"):
            raise ValueError(
                f"unknown comm wire_dtype {self.wire_dtype!r}; one of "
                "bfloat16|float16 (or None for the leaf dtype)")
        if self.byte_budget_mb < 0:
            raise ValueError(
                f"comm byte_budget_mb must be >= 0, got "
                f"{self.byte_budget_mb}")
        if self.min_codec_bytes < 0:
            raise ValueError(
                f"comm min_codec_bytes must be >= 0, got "
                f"{self.min_codec_bytes}")
        if self.chunk <= 0 or self.chunk % 2:
            raise ValueError(
                f"comm chunk must be a positive even count, got "
                f"{self.chunk}")
        if self.error_feedback not in ("on", "off"):
            raise ValueError(
                f"unknown comm error_feedback {self.error_feedback!r}; "
                "one of on|off")


@dataclass(frozen=True)
class ExperimentConfig:
    """Top-level experiment description = the notebook form cell, typed."""

    name: str = "experiment"
    seed: int = 2022
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimizerConfig = field(default_factory=OptimizerConfig)
    federated: FederatedConfig | None = None
    gossip: GossipConfig | None = None
    seqlm: SeqLMConfig | None = None
    faults: FaultConfig | None = None
    # Fault injection & recovery (dopt.faults.FaultPlan): crashes,
    # stragglers, partitions, Byzantine corruption for the
    # federated/gossip engines.  None = fault-free (bit-identical to a
    # config without the field).
    robust: RobustConfig | None = None
    # Byzantine-robust aggregation & quarantine (dopt.robust).  None =
    # the plain masked-mean programs (bit-identical to pre-robust runs;
    # non-finite updates are still screened from the federated mean).
    population: PopulationConfig | None = None
    # Client population registry (dopt.population): per-round cohort
    # sampling from a 1k–10k client population with hierarchical
    # (multi-wave) aggregation.  None = the classic worker==lane
    # engines, bit-identical to pre-population programs.
    comm: CommConfig | None = None
    # Communication substrate schedule: per-bucket wire codecs inside
    # the scatter path (dopt.parallel.collectives.make_codec_plan).
    # None = the uncompressed wire, bit-identical to pre-comm programs.
    # Execution backend — the pluggable Worker(backend=...) boundary:
    # "jax" runs the TPU/mesh engines; "torch" runs the SAME experiment
    # on the faithful sequential CPU oracle (dopt.engine.torch_backend)
    # — identical init, plans, sampling streams, holdout — for
    # cross-backend trajectory comparison.  Anything else raises.
    backend: str = "jax"
    # Mesh shape: workers are folded onto devices; workers_per_device>1
    # vmaps multiple worker lanes onto one chip (SURVEY §7 hard parts).
    mesh_devices: int | None = None   # None -> all available
    mesh_hosts: int | None = None
    # None -> 1-D worker mesh.  Set to H for a 2-D (hosts × ici) hybrid
    # mesh (dopt.parallel.multihost): on a real multi-slice job the
    # outer axis crosses DCN; single-process it partitions local devices
    # into H virtual hosts (same program, testable anywhere).

    def replace(self, **kw: Any) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)

    @property
    def num_users(self) -> int:
        return self.data.num_users


def _filter_kwargs(cls: type, d: Mapping[str, Any]) -> dict[str, Any]:
    names = {f.name for f in dataclasses.fields(cls)}
    return {k: v for k, v in d.items() if k in names}


def from_reference_args(args: Mapping[str, Any]) -> ExperimentConfig:
    """Build an ``ExperimentConfig`` from a reference-style flat args dict.

    Accepts the exact key names the reference notebooks use (cells 8/11:
    num_users, local_ep, local_bs, lr, momentum, model, dataset, iid,
    shards, rho, seed, topology, mode, frac, rounds, eps) so published
    experiment dictionaries can be replayed verbatim.
    """
    def _get(key: str, default):
        v = args.get(key)
        return default if v is None else v

    model_name = str(_get("model", "")).lower()
    dataset = str(_get("dataset", "mnist")).lower()
    num_classes = 10
    if dataset in ("cifar", "cifar10"):
        dataset = "cifar10"
        input_shape: tuple[int, ...] = (32, 32, 3)
        default_model = "model3"
    elif dataset == "cifar100":
        input_shape = (32, 32, 3)
        default_model = "model3"
        num_classes = 100
    elif dataset == "a9a":
        input_shape = (123,)   # LIBSVM a9a: 123 binary features, 2 classes
        default_model = "logistic"
        num_classes = 2
    elif dataset == "synthetic":
        input_shape = tuple(_get("input_shape", (28, 28, 1)))
        default_model = "mlp"
    else:
        input_shape = (28, 28, 1)
        default_model = "model1"
    if model_name in ("", "none"):
        model_name = default_model

    if args.get("unequal"):
        raise ValueError(
            "unequal splits are not supported (the reference has none; "
            "both its partitioner families produce equal-size shards)")
    data = DataConfig(
        dataset=dataset,
        iid=bool(_get("iid", True)),
        shards=int(_get("shards", 2)),
        num_users=int(_get("num_users", 8)),
        data_dir=args.get("data_dir"),
    )
    model = ModelConfig(
        model=model_name,
        num_classes=num_classes,
        input_shape=input_shape,
        faithful=bool(_get("faithful", True)),
    )
    optim = OptimizerConfig(
        lr=float(_get("lr", 0.01)),
        momentum=float(_get("momentum", 0.5)),
        rho=float(_get("rho", 0.1)),
        optimizer=str(_get("optimizer", "sgd")),
    )
    federated = None
    gossip = None
    # Reference DotDict form cells carry unused keys with value None;
    # route on a *usable* topology value, not key presence.
    if args.get("topology") or str(_get("paradigm", "")) == "gossip":
        gossip = GossipConfig(
            algorithm=str(_get("algorithm", "dsgd")),
            topology=str(_get("topology", "circle")),
            mode=str(_get("mode", "stochastic")),
            rounds=int(_get("rounds", 10)),
            local_ep=int(_get("local_ep", 4)),
            local_bs=int(_get("local_bs", 128)),
            eps=int(_get("eps", 1)),
        )
    else:
        federated = FederatedConfig(
            algorithm=str(_get("algorithm", "fedavg")),
            frac=float(_get("frac", 0.1)),
            rounds=int(_get("rounds", 20)),
            local_ep=int(_get("local_ep", 10)),
            local_bs=int(_get("local_bs", 50)),
        )
    return ExperimentConfig(
        name=str(args.get("name", "experiment")),
        seed=int(args.get("seed", 2022)),
        data=data,
        model=model,
        optim=optim,
        federated=federated,
        gossip=gossip,
    )


def exp_details(cfg: ExperimentConfig) -> str:
    """Human-readable config dump (reference ``exp_details``, utils.py:147-165)."""
    lines = [f"Experiment: {cfg.name}", f"  seed      : {cfg.seed}", f"  backend   : {cfg.backend}"]
    for section in ("data", "model", "optim", "federated", "gossip", "faults",
                    "robust", "population", "comm"):
        sub = getattr(cfg, section)
        if sub is None:
            continue
        lines.append(f"  [{section}]")
        for f in dataclasses.fields(sub):
            lines.append(f"    {f.name:12s}: {getattr(sub, f.name)}")
    return "\n".join(lines)
