"""dopt.serve — the resident elastic trainer and its control plane.

Covers the control-plane command semantics (apply-at-round-boundary,
whitelist rejection, ledgered ``control`` events), the serve loop's
drain/checkpoint/resume bit-identity (SIGTERM-equivalent restart vs an
uninterrupted run of the same command schedule), in-process monitor
parity vs file tailing, the checkpoint_cadence rule's header-sourced
expectation, and the ``dopt.obs.serve`` port-0/state-file/SIGTERM
satellite.  The multi-process rolling-restart leg (real
``jax.distributed`` + gloo + a real SIGTERM) is marked ``slow``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                         ModelConfig, OptimizerConfig)
from dopt.serve import (CONFIG_WHITELIST, CommandQueue, ControlLedger,
                        EX_RESTART, ServeDaemon, build_serve_trainer,
                        make_command, validate_command)
from dopt.serve.control import (apply_config_change, control_ledger_row,
                                replay_effects)

REPO = Path(__file__).resolve().parent.parent


def tiny_gossip_cfg(seed: int = 5, rounds: int = 4) -> ExperimentConfig:
    return ExperimentConfig(
        name="serve-test", seed=seed,
        data=DataConfig(dataset="synthetic", num_users=8, iid=True,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=rounds, local_ep=1,
                            local_bs=32))


# ---------------------------------------------------------------- schema

def test_command_schema_accept_reject():
    ok = make_command("config", key="optim.lr", value=0.05, at_round=3,
                      id="lr")
    assert ok["v"] == 1 and ok["cmd"] == "config"
    validate_command(make_command("membership", worker=3, action="leave"))
    validate_command(make_command("drain", restart=True))
    validate_command(make_command("checkpoint"))
    with pytest.raises(ValueError, match="unknown command"):
        validate_command({"v": 1, "cmd": "reboot"})
    with pytest.raises(ValueError, match="version"):
        validate_command({"v": 2, "cmd": "drain"})
    with pytest.raises(ValueError, match="not whitelisted"):
        make_command("config", key="gossip.topology", value=1)
    with pytest.raises(ValueError, match="integer"):
        make_command("config", key="population.cohort", value=3.5)
    with pytest.raises(ValueError, match="lr must be > 0"):
        make_command("config", key="optim.lr", value=0.0)
    with pytest.raises(ValueError, match="worker"):
        make_command("membership", worker=-1, action="leave")
    with pytest.raises(ValueError, match="action"):
        make_command("membership", worker=1, action="evict")
    with pytest.raises(ValueError, match="at_round"):
        make_command("checkpoint", at_round=-2)
    assert set(CONFIG_WHITELIST) == {"optim.lr", "population.cohort",
                                     "checkpoint_every"}


def test_command_queue_incremental(tmp_path):
    q = CommandQueue(tmp_path / "commands.jsonl")
    q.submit(make_command("membership", worker=1, action="leave", id="a"))
    q.submit(make_command("checkpoint"))
    cmds, rejects = q.poll()
    assert [c["id"] for c in cmds] == ["a", "q2"] and not rejects
    assert q.poll() == ([], [])   # nothing new
    # External writers can append raw lines; malformed ones become
    # reject records instead of desynchronizing the tail.
    with open(tmp_path / "commands.jsonl", "a") as f:
        f.write("this is not json\n")
        f.write(json.dumps({"v": 1, "cmd": "config", "key": "seed",
                            "value": 1}) + "\n")
        f.write(json.dumps(make_command("drain")) + "\n")
    cmds, rejects = q.poll()
    assert [c["cmd"] for c in cmds] == ["drain"]
    assert len(rejects) == 2
    assert "not JSON" in rejects[0]["reason"]
    assert "whitelisted" in rejects[1]["reason"]
    # A fresh tail (daemon restart) re-derives the same queue ids.
    q2 = CommandQueue(tmp_path / "commands.jsonl")
    cmds2, rejects2 = q2.poll()
    assert [c["id"] for c in cmds2] == ["a", "q2", "q5"]
    assert len(rejects2) == 2


def test_control_ledger_replay(tmp_path):
    path = tmp_path / "applied.jsonl"
    led = ControlLedger(path)
    led.append({"v": 1, "id": "m1", "cmd": "membership", "worker": 2,
                "action": "leave", "status": "applied", "round": 3})
    led.append({"v": 1, "id": "lr", "cmd": "config", "key": "optim.lr",
                "value": 0.05, "status": "applied", "round": 5})
    led.append({"v": 1, "id": "bad", "cmd": None, "status": "rejected",
                "round": 5, "reason": "nope"})
    led.append({"v": 1, "id": "ce", "cmd": "config",
                "key": "checkpoint_every", "value": 3,
                "status": "applied", "round": 6})
    # Superseding record for a re-applied command: last one wins.
    led.append({"v": 1, "id": "m1", "cmd": "membership", "worker": 2,
                "action": "leave", "status": "applied", "round": 4})
    led.close()
    records = ControlLedger.replay(path)
    assert [r["id"] for r in records] == ["m1", "lr", "bad", "ce"]
    assert records[0]["round"] == 4   # superseded
    fx = replay_effects(records, up_to_round=5)
    assert fx["membership"] == [(4, 2, False)]
    assert fx["config"] == [(5, "optim.lr", 0.05)]
    assert fx["checkpoint_every"] is None   # round 6 > checkpoint round
    assert fx["processed"] == {"m1", "lr", "bad"}
    fx_all = replay_effects(records, up_to_round=10)
    assert fx_all["checkpoint_every"] == 3


def test_torn_tails_healed_on_append(tmp_path):
    """A hard-killed writer's newline-less partial line must never
    swallow the next append: the queue terminates it (the torn line
    becomes a reject, the new command its own line) and the ledger
    skips it on replay instead of discarding everything after it."""
    qp = tmp_path / "commands.jsonl"
    qp.write_text('{"v": 1, "cmd": "checkpo')   # torn mid-write
    q = CommandQueue(qp)
    q.submit(make_command("drain", id="d1"))
    cmds, rejects = q.poll()
    assert [c["id"] for c in cmds] == ["d1"]
    assert len(rejects) == 1 and "not JSON" in rejects[0]["reason"]

    lp = tmp_path / "applied.jsonl"
    led = ControlLedger(lp)
    led.append({"v": 1, "id": "a", "cmd": "pause", "status": "applied",
                "round": 1})
    led.close()
    with open(lp, "a") as f:
        f.write('{"v": 1, "id": "torn", "cmd": "resu')   # torn mid-append
    led2 = ControlLedger(lp)
    led2.append({"v": 1, "id": "b", "cmd": "resume", "status": "applied",
                 "round": 2})
    led2.close()
    assert [r["id"] for r in ControlLedger.replay(lp)] == ["a", "b"]


def test_apply_config_change_whitelist():
    cfg = tiny_gossip_cfg()
    out = apply_config_change(cfg, "optim.lr", 0.025)
    assert out.optim.lr == 0.025 and cfg.optim.lr == 0.1
    with pytest.raises(ValueError, match="whitelisted"):
        apply_config_change(cfg, "seed", 1)


def test_control_ledger_row_shapes():
    row = control_ledger_row(make_command("config", key="optim.lr",
                                          value=0.05, id="x"), 7)
    assert row == {"round": 7, "worker": -1, "kind": "control",
                   "action": "applied_config_optim.lr=0.05"}
    row = control_ledger_row(make_command("membership", worker=3,
                                          action="join"), 9)
    assert row["worker"] == 3 and row["action"] == "applied_membership_join"


# ------------------------------------------------- membership plumbing

def test_membership_log_ordering_and_flags():
    from dopt.faults import FaultPlan, MembershipLog

    log = MembershipLog()
    log.add(2, 1, False)
    with pytest.raises(ValueError, match="round order"):
        log.add(1, 0, False)
    with pytest.raises(ValueError, match="worker >= 0"):
        log.add(3, -1, True)
    plan = FaultPlan(4, None, membership=log)
    assert plan.active and plan.has_churn and plan.affects_matrix
    assert not plan.may_straggle and not plan.has_corrupt
    assert list(np.nonzero(plan.away_for_round(2))[0]) == [1]
    assert not plan.away_for_round(1).any()
    # Default plans untouched: the scripted-run off-path guarantee.
    bare = FaultPlan(4, None)
    assert not bare.active and not bare.has_churn and bare.cfg is None


def test_membership_population_rejected():
    import dataclasses

    from dopt.config import PopulationConfig
    from dopt.engine import GossipTrainer
    from dopt.faults import MembershipLog

    cfg = tiny_gossip_cfg()
    cfg = dataclasses.replace(cfg, population=PopulationConfig(
        clients=8, cohort=8))
    with pytest.raises(ValueError, match="does not compose"):
        GossipTrainer(cfg, membership=MembershipLog())


def test_build_serve_trainer_rejects_torch_and_seqlm():
    import dataclasses

    cfg = dataclasses.replace(tiny_gossip_cfg(), backend="torch")
    with pytest.raises(ValueError, match="jax engines only"):
        build_serve_trainer(cfg, None)


# ------------------------------------------------------- the serve loop

class _TermAt(ServeDaemon):
    """SIGTERM-equivalent at an exact boundary (deterministic tests
    can't rely on signal delivery timing)."""

    def __init__(self, *a, term_at=None, **kw):
        super().__init__(*a, **kw)
        self._term_at = term_at

    def boundary(self, trainer):
        if self._term_at is not None and trainer.round == self._term_at:
            self._term = True
            self._term_signal = self.on_term
        return super().boundary(trainer)


def _seed_commands(state_dir: Path) -> None:
    q = CommandQueue(Path(state_dir) / "commands.jsonl")
    q.submit(make_command("membership", worker=3, action="leave",
                          at_round=1, id="m1"))
    q.submit(make_command("config", key="optim.lr", value=0.05,
                          at_round=2, id="lr1"))
    q.submit(make_command("membership", worker=3, action="join",
                          at_round=4, id="m2"))
    q.submit(make_command("checkpoint", at_round=3, id="ck"))


def test_serve_boundaries_and_restart_bit_identity(tmp_path):
    """The acceptance core, in-process: a served run applies commands
    at their pinned boundaries (ledgered control rows + churn rows +
    deterministic control events), and a SIGTERM-equivalent restart
    mid-run resumes BIT-EXACTLY — History, fault ledger and canonical
    telemetry stream identical to the uninterrupted run."""
    from dopt.obs import HealthMonitor, JsonlSink, canonical, check_stream

    rounds = 6

    # Leg A: uninterrupted.
    dir_a = tmp_path / "a"
    _seed_commands(dir_a)
    da = ServeDaemon(tiny_gossip_cfg(), dir_a, checkpoint_every=2,
                     max_rounds=rounds, admin_port=None).start()
    assert da.serve() == 0
    hist = da.trainer.history
    ctl = [r for r in hist.faults if r["kind"] == "control"]
    assert [(r["round"], r["action"]) for r in ctl] == [
        (1, "applied_membership_leave"),
        (2, "applied_config_optim.lr=0.05"),
        (3, "applied_checkpoint"),
        (4, "applied_membership_join"),
    ]
    churn = [(r["round"], r["action"]) for r in hist.faults
             if r["kind"] == "churn"]
    assert (1, "left") in churn and (4, "rejoined") in churn
    assert any("shard_adopted" in a for _, a in churn)
    assert da.trainer.cfg.optim.lr == 0.05   # rebuild took effect
    ev_a = JsonlSink.read(dir_a / "metrics.jsonl")
    summary = check_stream(ev_a)
    assert summary["rounds"] == rounds
    assert summary["kinds"]["control"] == 4
    # final.json is the drain artifact the soak harness consumes.
    final = json.loads((dir_a / "final.json").read_text())
    assert final["round"] == rounds and final["history"] == hist.rows
    assert final["report"]["verdict"] == "healthy"

    # Leg B: restart at boundary 3 (post-rebuild), then resume.
    dir_b = tmp_path / "b"
    _seed_commands(dir_b)
    db1 = _TermAt(tiny_gossip_cfg(), dir_b, checkpoint_every=2,
                  max_rounds=rounds, admin_port=None, term_at=3).start()
    assert db1.serve() == EX_RESTART
    db2 = ServeDaemon(tiny_gossip_cfg(), dir_b, checkpoint_every=2,
                      max_rounds=rounds, admin_port=None).start()
    assert db2._resumed and db2.trainer.round == 3
    assert db2.trainer.cfg.optim.lr == 0.05   # replayed from the ledger
    assert db2.serve() == 0
    assert db2.restarts == 1

    assert db2.trainer.history.rows == hist.rows
    assert db2.trainer.history.faults == hist.faults
    ev_b = JsonlSink.read(dir_b / "metrics.jsonl")
    check_stream(ev_b)
    assert canonical(ev_b) == canonical(ev_a)

    # Zero false positives (stock rules) and alert parity between the
    # two legs' streams.
    ma, mb = HealthMonitor(), HealthMonitor()
    ma.feed(ev_a)
    mb.feed(ev_b)
    assert ma.report().alerts == 0 and ma.report().verdict == "healthy"
    assert ma.canonical_alerts() == mb.canonical_alerts()


def test_serve_rejects_unwhitelisted_and_out_of_range(tmp_path):
    """Rejected commands are recorded in the applied ledger but never
    ledgered as control rows or events."""
    from dopt.obs import JsonlSink

    state = tmp_path / "s"
    state.mkdir()
    with open(state / "commands.jsonl", "w") as f:
        f.write(json.dumps({"v": 1, "cmd": "config", "key": "seed",
                            "value": 9, "id": "bad-key"}) + "\n")
        f.write(json.dumps(make_command("membership", worker=99,
                                        action="leave",
                                        id="bad-worker")) + "\n")
    d = ServeDaemon(tiny_gossip_cfg(), state, checkpoint_every=0,
                    max_rounds=2, admin_port=None).start()
    assert d.serve() == 0
    assert not any(r["kind"] == "control"
                   for r in d.trainer.history.faults)
    records = {r["id"]: r for r in ControlLedger.replay(
        state / "applied.jsonl")}
    assert records["bad-key"]["status"] == "rejected"
    assert records["bad-worker"]["status"] == "rejected"
    assert "lane fleet" in records["bad-worker"]["reason"]
    evs = JsonlSink.read(state / "metrics.jsonl")
    assert not any(e["kind"] == "control" for e in evs)


def test_auto_pause_on_drop_rate_critical(tmp_path):
    """A drop_rate-critical alert auto-pauses admission: the daemon
    self-applies a ledgered pause command and join commands are
    rejected until a resume."""
    from dopt.obs import HealthMonitor

    d = ServeDaemon(tiny_gossip_cfg(), tmp_path, admin_port=None)
    d.monitor = HealthMonitor([])
    d.monitor.alerts = [{"kind": "alert", "rule": "drop_rate_critical",
                         "severity": "critical", "round": 2}]
    trainer = SimpleNamespace(num_workers=8, round=3,
                              history=SimpleNamespace(faults=[]),
                              save=lambda path: None)
    directive = d._decide(3, trainer)
    assert [c["cmd"] for c in directive["apply"]] == ["pause"]
    assert directive["auto"] == ["auto-pause-3"]
    assert d._execute(directive, trainer) == "run"
    assert d.paused
    rec = ControlLedger.replay(tmp_path / "applied.jsonl")[0]
    assert rec["auto"] is True and rec["status"] == "applied"
    assert trainer.history.faults == [
        {"round": 3, "worker": -1, "kind": "control",
         "action": "applied_pause"}]
    # While paused, a join is rejected at the boundary...
    d.queue.submit(make_command("membership", worker=1, action="join",
                                id="j1"))
    directive = d._decide(4, trainer)
    assert directive["apply"] == []
    assert [r["id"] for r in directive["rejected"]] == ["j1"]
    assert "paused" in directive["rejected"][0]["reason"]
    d._execute(directive, trainer)
    # ...and flows again after a resume.
    d.queue.submit(make_command("resume", id="r1"))
    d.queue.submit(make_command("membership", worker=1, action="join",
                                id="j2"))
    directive = d._decide(5, trainer)
    assert [c["id"] for c in directive["apply"]] == ["r1", "j2"]


def test_serve_rules_escalation_silent_by_default():
    from dopt.serve import serve_rules

    rules = serve_rules()
    names = [r.name for r in rules]
    assert "drop_rate_critical" in names and "drop_rate" in names
    esc = next(r for r in rules if r.name == "drop_rate_critical")
    assert esc.severity == "critical"


# --------------------------------- checkpoint_cadence from the header

def _hdr(round_=0, **kw):
    from dopt.obs import make_event

    return make_event("run", engine="gossip", name="t", round=round_, **kw)


def _round(t):
    from dopt.obs import make_event

    return make_event("round", round=t, engine="gossip",
                      metrics={"avg_train_loss": 1.0})


def test_checkpoint_cadence_reads_run_header():
    from dopt.obs import HealthMonitor, make_event

    # Header declares every-2; no checkpoint events ever: overdue at
    # round 4 (2 + slack 1 exceeded).
    mon = HealthMonitor()
    mon.feed([_hdr(checkpoint_every=2)] + [_round(t) for t in range(5)])
    fired = [a["rule"] for a in mon.alerts]
    assert fired == ["checkpoint_cadence"]
    # Same stream, checkpoints on cadence: silent.
    mon2 = HealthMonitor()
    evs = [_hdr(checkpoint_every=2)]
    for t in range(5):
        evs.append(_round(t))
        if t % 2 == 1:
            evs.append(make_event("checkpoint", round=t))
    mon2.feed(evs)
    assert mon2.alerts == []
    # No header field, no explicit every: the rule stays inactive.
    mon3 = HealthMonitor()
    mon3.feed([_hdr()] + [_round(t) for t in range(8)])
    assert mon3.alerts == []


def test_checkpoint_cadence_follows_control_event():
    from dopt.obs import HealthMonitor, make_event

    mon = HealthMonitor()
    evs = [_hdr(checkpoint_every=10)]
    evs += [_round(t) for t in range(3)]
    # A live cadence change to every-1 makes round 6 overdue even
    # though the header said 10.
    evs.append(make_event("control", round=3, cmd="config",
                          key="checkpoint_every", value=1, id="ce"))
    evs += [_round(t) for t in range(3, 7)]
    mon.feed(evs)
    assert [a["rule"] for a in mon.alerts] == ["checkpoint_cadence"]
    # Monitor state round-trips the context (restart-safe).
    st = json.loads(json.dumps(mon.state()))
    mon2 = HealthMonitor(state=st)
    assert mon2.ctx.checkpoint_every == 1


def test_attach_stamps_checkpoint_every(tmp_path):
    from dopt.obs import MemorySink, Telemetry, attach

    tele = Telemetry([MemorySink()])
    trainer = SimpleNamespace(round=0, num_workers=4,
                              timers=SimpleNamespace(tracer=None),
                              cfg=SimpleNamespace(name="x"),
                              telemetry=None, engine_kind="gossip")
    attach(trainer, tele, checkpoint_every=4)
    hdr = tele.sinks[0].events[0]
    assert hdr["kind"] == "run" and hdr["checkpoint_every"] == 4
    tele2 = Telemetry([MemorySink()])
    attach(trainer, tele2)
    assert "checkpoint_every" not in tele2.sinks[0].events[0]


# ------------------------------------------- obs.serve CLI satellite

def test_obs_serve_port0_statefile_sigterm(tmp_path):
    """`python -m dopt.obs.serve --port 0`: the ephemeral port is
    announced on stdout and in --state-file, the endpoint serves, and
    SIGTERM shuts down gracefully (exit 0, state file removed)."""
    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as f:
        for ev in [_hdr(), _round(0), _round(1)]:
            f.write(json.dumps(ev) + "\n")
    state = tmp_path / "endpoint.json"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dopt.obs.serve", str(metrics),
         "--port", "0", "--state-file", str(state)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO)
    try:
        line = proc.stdout.readline()
        info = json.loads(line)
        assert info["port"] > 0 and info["pid"] == proc.pid
        deadline = time.time() + 10
        while not state.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert json.loads(state.read_text())["port"] == info["port"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{info['port']}/healthz",
                timeout=10) as r:
            body = json.loads(r.read())
        assert body["rounds"] == 2
        os.kill(proc.pid, signal.SIGTERM)
        rc = proc.wait(timeout=15)
        assert rc == 0
        assert not state.exists()
    finally:
        proc.stdout.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# --------------------------------------------------- decoupled fleets

def test_lanes_of_covers_every_lane_once():
    assert list(ServeDaemon.lanes_of(0, 2, 8)) == [0, 1, 2, 3]
    assert list(ServeDaemon.lanes_of(1, 2, 8)) == [4, 5, 6, 7]
    # uneven split: still a partition, in order
    cover = [i for r in range(3) for i in ServeDaemon.lanes_of(r, 3, 8)]
    assert cover == list(range(8))


def test_decoupled_and_spmd_mutually_exclusive(tmp_path):
    with pytest.raises(ValueError, match="mutually exclusive"):
        ServeDaemon(tiny_gossip_cfg(), tmp_path, fleet_size=2,
                    num_processes=2)


def test_decoupled_liveness_leave_and_rejoin(tmp_path):
    """The decoupled control plane, in-process: a drained peer's
    departure stamp turns its lanes into ledgered auto-leaves at the
    survivor's next boundary (no timeout wait), and a fresh heartbeat
    turns them back into joins after the survivor resumes."""
    fleet = tmp_path

    # Rank 1 runs to drain; _finalize stamps its heartbeat 'drained'.
    d1 = ServeDaemon(tiny_gossip_cfg(), fleet / "p1", checkpoint_every=0,
                     max_rounds=2, admin_port=None, fleet_rank=1,
                     fleet_size=2, fleet_dir=fleet,
                     peer_timeout_s=60.0).start()
    assert d1.serve() == 0
    stamp = json.loads((fleet / "liveness-p1.json").read_text())
    assert stamp["status"] == "drained" and stamp["rank"] == 1

    # Rank 0 sees the stamp at its first boundary: every rank-1 lane
    # leaves, ledgered auto like the drop_rate auto-pause.
    d0 = ServeDaemon(tiny_gossip_cfg(), fleet / "p0", checkpoint_every=2,
                     max_rounds=2, admin_port=None, fleet_rank=0,
                     fleet_size=2, fleet_dir=fleet,
                     peer_timeout_s=60.0).start()
    assert d0.serve() == 0
    recs = {r["id"]: r for r in ControlLedger.replay(
        fleet / "p0" / "applied.jsonl")}
    for i in (4, 5, 6, 7):
        rec = recs[f"auto-liveness-leave-r0-w{i}"]
        assert rec["status"] == "applied" and rec["auto"] is True
    away = d0.membership.away_at(2, 8)
    assert list(np.nonzero(away)[0]) == [4, 5, 6, 7]
    churn = [(r["worker"], r["action"]) for r in d0.trainer.history.faults
             if r["kind"] == "churn" and r["action"] == "left"]
    assert {w for w, _ in churn} == {4, 5, 6, 7}

    # Peer comes back (fresh heartbeat, new pid): the resumed rank 0
    # replays its ledger (lanes still away) and auto-joins them.
    (fleet / "liveness-p1.json").write_text(json.dumps(
        {"pid": 999999, "rank": 1, "round": 2, "status": "serving",
         "ts": time.time()}))
    d0b = ServeDaemon(tiny_gossip_cfg(), fleet / "p0", checkpoint_every=2,
                      max_rounds=4, admin_port=None, fleet_rank=0,
                      fleet_size=2, fleet_dir=fleet,
                      peer_timeout_s=60.0).start()
    assert d0b._resumed and d0b.trainer.round == 2
    assert list(np.nonzero(d0b.membership.away_at(2, 8))[0]) == [4, 5, 6, 7]
    assert d0b.serve() == 0
    recs = {r["id"]: r for r in ControlLedger.replay(
        fleet / "p0" / "applied.jsonl")}
    for i in (4, 5, 6, 7):
        assert recs[f"auto-liveness-join-r2-w{i}"]["status"] == "applied"
    assert not d0b.membership.away_at(4, 8).any()


def test_await_directive_timeout_diagnostics(tmp_path):
    """The follower's directive-barrier timeout names the leader's
    heartbeat age and the last published directive — the two bits that
    tell a dead leader from a slow one."""
    d = ServeDaemon(tiny_gossip_cfg(), tmp_path, admin_port=None,
                    process_id=1, num_processes=2,
                    directive_poll_s=0.01, directive_max_polls=4)
    with pytest.raises(RuntimeError, match="no heartbeat file"):
        d._await_directive(0, 3)
    # With a leader heartbeat and a stale published directive, the
    # error carries both (age + last seq) plus the triage guidance.
    (tmp_path / "liveness-p0.json").write_text(json.dumps(
        {"pid": 1, "rank": 0, "round": 7, "status": "serving",
         "ts": time.time() - 5.0}))
    (tmp_path / "epoch").mkdir()
    (tmp_path / "epoch" / "000004-7.json").write_text("{}")
    with pytest.raises(RuntimeError) as ei:
        d._await_directive(5, 8)
    msg = str(ei.value)
    assert "heartbeat" in msg and "status 'serving'" in msg
    assert "000004-7" in msg and "leader is gone" in msg


# ------------------------------------------------- multi-process legs

@pytest.mark.slow
def test_multiprocess_serve_rolling_restart(tmp_path):
    """REAL fleet: 2 jax.distributed processes (gloo), drain at 8
    rounds after surviving a live config-change rebuild (the
    leader-directive barrier revisits a boundary — the sequence-keyed
    directive path) and a SIGTERM-driven rolling restart of a follower
    — the fleet quiesces at the boundary, checkpoints once, respawns
    as the next generation, and resumes to a healthy drain."""
    state = tmp_path / "fleet"
    CommandQueue(state / "commands.jsonl").submit(
        make_command("config", key="optim.lr", value=0.05, at_round=2,
                     id="fleet-lr"))
    cmd = [sys.executable, "-m", "dopt.serve", "--preset", "baseline1",
           "--state-dir", str(state),
           "--set", "data.dataset=synthetic",
           "--set", "data.synthetic_train_size=256",
           "--set", "data.synthetic_test_size=64",
           "--set", "model.model=mlp", "--set", "model.faithful=false",
           "--set", "gossip.local_ep=1", "--set", "gossip.local_bs=32",
           "--num-users", "8", "--max-rounds", "40",
           "--checkpoint-every", "5", "--no-admin",
           "--num-processes", "2", "--devices-per-proc", "2"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    sup = subprocess.Popen(cmd, env=env, cwd=REPO,
                           stdout=subprocess.PIPE,
                           stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 420
        killed = False
        while sup.poll() is None:
            assert time.time() < deadline, "fleet timed out"
            time.sleep(0.1)
            status = state / "serve.json"
            if killed or not status.exists():
                continue
            try:
                st = json.loads(status.read_text())
            except ValueError:
                continue
            if st.get("status") == "serving" \
                    and 1 <= st.get("round", 0) <= 30:
                # No leading dashes in the pattern: pgrep would parse
                # them as its own options.
                out = subprocess.run(
                    ["pgrep", "-f",
                     f"state-dir {state}.*process-id 1"],
                    capture_output=True, text=True)
                pids = [int(p) for p in out.stdout.split()]
                if pids:
                    os.kill(pids[0], signal.SIGTERM)
                    killed = True
        log = sup.communicate()[0]
        assert sup.returncode == 0, \
            f"supervisor rc={sup.returncode}\n--- output ---\n{log[-4000:]}"
        assert killed, "never caught the fleet inside the SIGTERM window"
        final = json.loads((state / "final.json").read_text())
        assert final["round"] == 40
        assert final["report"]["verdict"] == "healthy"
        assert any(r["kind"] == "control"
                   and "optim.lr" in r["action"]
                   for r in final["fault_ledger"])
        assert final["restarts"] >= 1
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait()
