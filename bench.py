"""dopt benchmark — gossip rounds/sec on the reference's P2 workload.

Reproduces the reference's gossip experiment shape (`Weighted
Average.ipynb` cell 11: 6 workers, Model1 1.66M params, MNIST-sized
data, non-IID 2 shards/user, local_ep=4, local_bs=128, circle topology,
stochastic mixing) and measures steady-state gossip rounds per second on
the available accelerator.

Two modes are measured in one run:
  * fast      — the TPU-native configuration: bfloat16 compute, native
                C++ batch planner, all rounds fused into one lax.scan
                dispatch.  This is the headline number.
  * faithful  — float32 with the numpy (PCG64) batch planner: the
                torch-oracle-parity configuration.  Reported alongside.
Both train the identical faithful objective (double-softmax head),
algorithm, round order (consensus → eval → local epochs), data
partition, and mixing matrices.  The modes differ in compute dtype AND
in batch order (the native planner draws from its own xoshiro stream),
so the reported accuracies are a sanity check that the fast mode trains
equally well — not a controlled single-variable dtype ablation.

Baseline: the reference runs ~10 rounds in ~800s on Colab
(BASELINE.md: "Gossip throughput (derived) ~0.012 rounds/s").  Data is
synthetic at exactly MNIST scale (60,000 train / 10,000 test samples,
28x28x1) because this environment has no network egress; per-round
FLOPs and communication volume match the real workload.

What bounds MFU (~21% of bf16 peak on a v5e chip, measured): the round
is 316 dependent SGD steps (79 steps/epoch x 4 epochs) over a 768-row
effective batch (6 worker lanes x 128).  Round 4 removed the three
structural overheads (results/trace_headline.json before/after):
per-step minibatch gathers — 18% of device time, now ~1% via flat
[N, F] resident data + slab gathers; select_and_scatter maxpool
backward — 12%, replaced by a reshape-max whose VJP is an elementwise
eq-mask; and vmap-over-workers conv lowering — replaced by the grouped
stacked forward (dopt.models.make_stacked_apply), which is where most
of the 1.74 -> 2.39 rounds/s came from.  What remains is the conv
stack itself (~50% of device time): Model1's conv1 has 1 input channel
(no MXU channel contraction to amortise activation traffic) and the
faithful 5x5 convs at 28x28 are activation-heavy relative to their
FLOPs.  Levers tried and rejected: pallas fused SGD update (breaks
XLA's gradient/update fusion, 1.6x slower), bf16 param storage (+11%
throughput but -10pt accuracy), carrying grouped-layout kernels
through the scan (XLA picks worse conv layouts, +6% device time).
Eval is evaluated OUTSIDE the measured window (it is a metric, not
the workload).

Round 6: the fast leg defaults to ``update_sharding="scatter"`` (the
bucketed reduce-scatter consensus/update hot path with the XLA
latency-hiding scheduler armed — arXiv:2004.13336 applied to the
mixing round; ``--update-sharding off`` reverts), the wall measurement
is outlier-hardened (min/max-trimmed median + a ``--max-spread`` retry
gate — the r5 27.4% raw spread made single-window walls meaningless),
and the traced blocks additionally report the conv / mixing-comm /
update fractions of device time (named-scope attribution,
``dopt.utils.profiling.classify_phase``) so the "conv fraction" claim
is measured, not guessed.

Round 7: the client-scale legs (dopt.population) — baseline3 with a
1k- and a 10k-client population registry, cohort-sampled onto the 16
lanes in waves with hierarchical (bucketed reduce-scatter)
aggregation.  Each leg prints its own JSON line with the
``clients_per_sec`` headline (cohort · rounds/sec — client visits
served per second) plus ``population``/``cohort_size``/``waves``
fields; ``--quick`` emits the 1k line as a CI artifact.

r07: the fused mix+update epilogue lands in the engines.  ``--fused on``
(default) measures the fast workload twice — ``fused_update`` off vs on,
both ``update_sharding='off'`` — and folds ``fused_rounds_per_sec`` +
``fused_speedup`` into the headline line and the ``--quick`` artifact
(CI asserts present-and-finite).  ``--hbm-reuse-check`` is the donation
proof: the fused workload at block=1 vs block=4, peak-memory gauge flat
to ±10% or nonzero exit.  The seqlm workload is promoted to a headline
leg (``scripts/bench_seqlm.py`` stays the standalone sweep tool): its
tokens/sec line rides every full run and appends to the ledger under
its own ``(seqlm_tokens_per_sec, device_kind)`` key.  ``--fused-modes``
is the standalone r07 mode (the r06 ``--topology-modes`` pattern): the
fused A/B on the backend-portable MLP gossip workload with the
hbm-reuse proof folded in, plus the seqlm leg, each appended under its
own ledger key.

Prints the main JSON line:
  {"metric": "...", "value": N, "unit": "rounds/sec", "vs_baseline": N,
   "conv_fraction": f, "comm_fraction": f, "update_fraction": f,
   "fused_rounds_per_sec": N, "fused_speedup": N,
   "clients_per_sec_1k": N, "clients_per_sec_10k": N, ...}
plus one JSON line per client-scale leg and one for the seqlm leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REFERENCE_ROUNDS_PER_SEC = 0.012  # BASELINE.md derived gossip throughput

# Model1 training FLOPs per sample (fwd + bwd ≈ 3 × fwd), analytic:
#   conv1 28×28×32×(5·5·1)  MACs = 627,200
#   conv2 14×14×64×(5·5·32) MACs = 10,035,200
#   fc1   3136×512          MACs = 1,605,632
#   fc2   512×10            MACs = 5,120
#   fwd = 2 × 12,273,152 FLOPs = 24.55 MFLOP; ×3 ≈ 73.6 MFLOP/sample.
MODEL1_TRAIN_FLOPS_PER_SAMPLE = 3 * 2 * 12_273_152

def _device_peak_flops() -> tuple[str, float | None]:
    """(device_kind, bf16 peak) — dopt.utils.profiling.device_peak_flops."""
    from dopt.utils.profiling import device_peak_flops

    return device_peak_flops()


def _config(*, fast: bool, train_size: int, test_size: int,
            faithful_model: bool = True, update_sharding: str = "off",
            prefetch: str = "off", diagnostics: str = "off",
            fused: str = "off"):
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)

    return ExperimentConfig(
        name="bench-dsgd-mnist" + ("-fast" if fast else "-faithful")
             + ("" if faithful_model else "-idiomatic"),
        seed=2028,
        data=DataConfig(dataset="mnist", num_users=6, iid=False, shards=2,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size,
                        plan_impl="native" if fast else "numpy"),
        model=ModelConfig(model="model1", faithful=faithful_model,
                          compute_dtype="bfloat16" if fast else "float32"),
        # The corrected-head objective has ~17x larger gradients than the
        # double-softmax it replaces, which puts the reference lr at the
        # edge of stability — bf16 rounding noise tipped whole runs into
        # 0.3-acc collapses (results/README.md).  Per-worker global-norm
        # clipping removes that on the bf16 leg ONLY: the faithful path
        # has no clipping (the reference has none), and the idiomatic
        # f32 leg stays unclipped too — it is the control showing the
        # instability is bf16-specific (f32 trains to 1.0 without clip).
        optim=OptimizerConfig(
            lr=0.01, momentum=0.5,
            clip_norm=1.0 if (fast and not faithful_model) else 0.0),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="stochastic", rounds=10, local_ep=4,
                            local_bs=128,
                            update_sharding=update_sharding,
                            prefetch=prefetch, diagnostics=diagnostics,
                            fused_update=fused),
    )


def _chaos_config(*, train_size: int, test_size: int,
                  prefetch: str = "off", diagnostics: str = "off"):
    """The degraded-network cocktail on the headline workload:
    msg_drop (lossy links) + stragglers + Byzantine scale-lies +
    quarantine armed.  Every one of these modes used to force
    per-round execution; all of them now ride the fused blocked scan,
    and ``gossip_rounds_per_sec_chaos`` tracks that the degraded path
    stays compute-bound rather than dispatch-bound (the north-star
    regime — decentralized methods only pay off when the degraded path
    is engineered to the happy path's throughput standard)."""
    from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                             GossipConfig, ModelConfig, OptimizerConfig,
                             RobustConfig)

    # baseline1-lossy-style workload (4-worker ring MNIST MLP): light
    # rounds, which is exactly where per-round execution was
    # dispatch-bound — the regime the fused chaos scan reclaims.  (The
    # model1 CNN legs above stay the compute-bound headline.)
    return ExperimentConfig(
        name="bench-chaos-baseline1-lossy",
        seed=2028,
        data=DataConfig(dataset="mnist", num_users=4, iid=False, shards=2,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size,
                        plan_impl="native"),
        model=ModelConfig(model="mlp", faithful=False,
                          compute_dtype="bfloat16"),
        optim=OptimizerConfig(lr=0.05, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=20, local_ep=2,
                            local_bs=64, prefetch=prefetch,
                            diagnostics=diagnostics),
        faults=FaultConfig(msg_drop=0.15, straggle=0.25, straggle_frac=0.5,
                           corrupt=0.15, corrupt_mode="scale",
                           corrupt_scale=10.0),
        robust=RobustConfig(quarantine_after=3, quarantine_rounds=5),
    )


def _measure_chaos(train_size: int, test_size: int, rounds: int,
                   repeats: int, telemetry=None,
                   prefetch: str = "off",
                   diagnostics: str = "off") -> dict:
    """Chaos-cocktail throughput, both execution paths: ``blocked``
    (all measured rounds in one fused lax.scan dispatch — the path this
    PR opened to degraded modes) and ``per_round`` (one jit dispatch +
    host sync per round — what every chaos mode was pinned to before).
    The ratio is the headline: fused blocks must make chaos runs
    dispatch-free, and the traces are pinned bit-identical across the
    two paths by tests/test_fused_chaos.py, so the speedup is free."""
    # Telemetry rides BOTH legs (each as its own stream segment):
    # emission happens inside the timed window, so telemetering only
    # one leg would skew the blocked-vs-per-round speedup ratio with
    # --metrics-out — the ratio must compare like with like.
    # Diagnostics (when armed) ride BOTH legs, like telemetry: the
    # speedup ratio must compare like with like.
    blocked = _measure(_chaos_config(train_size=train_size,
                                     test_size=test_size,
                                     prefetch=prefetch,
                                     diagnostics=diagnostics),
                       rounds, rounds, repeats, telemetry=telemetry)
    per_round = _measure(_chaos_config(train_size=train_size,
                                       test_size=test_size,
                                       diagnostics=diagnostics),
                         rounds, 1, repeats, telemetry=telemetry)
    return {
        "gossip_rounds_per_sec_chaos": round(blocked["rounds_per_sec"], 4),
        "chaos_host_gap_pct": round(blocked["host_gap_pct"], 2),
        "chaos_host_batch_plan_fraction": round(
            blocked["host_batch_plan_fraction"], 4),
        "chaos_prefetch": prefetch,
        "chaos_spread_pct": round(blocked["spread_pct"], 2),
        "chaos_avg_test_acc": round(blocked["avg_test_acc"], 4),
        "chaos_per_round_rounds_per_sec": round(
            per_round["rounds_per_sec"], 4),
        "chaos_speedup_vs_per_round": round(
            blocked["rounds_per_sec"] / per_round["rounds_per_sec"], 2),
        "chaos_samples_per_sec": round(blocked["samples_per_sec"], 1),
        # Un-prefixed on purpose: the quick artifact spreads this dict
        # into its top level, and the CI gate asserts bytes_on_wire is
        # present-and-finite there.
        "bytes_on_wire": blocked["bytes_on_wire"],
    }


def _topology_config(*, topology: str, mixing: str, train_size: int,
                     test_size: int, workers: int = 32,
                     prefetch: str = "off"):
    """The round-r06 mixing-pattern ablation workload: 32 worker lanes
    (folded onto however many devices exist), MLP on synthetic data,
    ONE local epoch of light steps — communication-dominated by
    construction, so the topology/mixing delta is what the wall
    measures rather than the conv stack."""
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)

    return ExperimentConfig(
        name=f"bench-topo-{topology}-{mixing}",
        seed=2028,
        data=DataConfig(dataset="synthetic", num_users=workers, iid=True,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size,
                        plan_impl="native"),
        model=ModelConfig(model="mlp", faithful=False,
                          compute_dtype="bfloat16"),
        optim=OptimizerConfig(lr=0.05, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology=topology,
                            mode="metropolis", mixing=mixing, rounds=20,
                            local_ep=1, local_bs=64, prefetch=prefetch),
    )


def _measure_topology_modes(*, train_size: int, test_size: int,
                            rounds: int, repeats: int, workers: int = 32,
                            telemetry=None, prefetch: str = "off",
                            max_spread: float = 0.0) -> dict:
    """Dense vs one-peer vs async at n=32 — the r06 headline delta.

    Three legs of the identical workload, differing ONLY in the
    consensus wire: ``dense`` (complete graph — the all_gather + [n, n]
    contraction path), ``one_peer`` (the one-peer exponential shift
    schedule: one ppermute peer per round, same asymptotic contraction
    over a period), and ``async`` (one-peer + staleness-1 mixing, where
    round r's communication overlaps round r+1's compute).  The
    headline ``value`` is the one-peer sync leg; the speedup ratios and
    per-leg accuracies ride alongside so the regress ledger tracks both
    the throughput win and that the cheap wire still trains."""
    kind, _ = _device_peak_flops()
    legs = {}
    for name, topology, mixing in (("dense", "complete", "sync"),
                                   ("one_peer", "one_peer_exp", "sync"),
                                   ("async", "one_peer_exp", "async")):
        legs[name] = _measure(
            _topology_config(topology=topology, mixing=mixing,
                             train_size=train_size, test_size=test_size,
                             workers=workers, prefetch=prefetch),
            rounds, rounds, repeats, max_spread=max_spread,
            telemetry=telemetry)
        print(f"# topology-modes {name}: "
              f"{legs[name]['rounds_per_sec']:.4f} r/s (spread "
              f"{legs[name]['spread_pct']:.1f}%, "
              f"acc={legs[name]['avg_test_acc']:.4f})", file=sys.stderr)
    dense, one_peer, asynk = legs["dense"], legs["one_peer"], legs["async"]
    return {
        "metric": f"gossip_topology_modes_dsgd_mlp_{workers}workers",
        "value": round(one_peer["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "workers": workers,
        "rounds_per_block": rounds,
        "device_kind": kind,
        "prefetch": prefetch,
        "dense_rounds_per_sec": round(dense["rounds_per_sec"], 4),
        "one_peer_rounds_per_sec": round(one_peer["rounds_per_sec"], 4),
        "async_rounds_per_sec": round(asynk["rounds_per_sec"], 4),
        "one_peer_speedup_vs_dense": round(
            one_peer["rounds_per_sec"] / dense["rounds_per_sec"], 3),
        "async_speedup_vs_dense": round(
            asynk["rounds_per_sec"] / dense["rounds_per_sec"], 3),
        "async_speedup_vs_one_peer": round(
            asynk["rounds_per_sec"] / one_peer["rounds_per_sec"], 3),
        "dense_avg_test_acc": round(dense["avg_test_acc"], 4),
        "one_peer_avg_test_acc": round(one_peer["avg_test_acc"], 4),
        "async_avg_test_acc": round(asynk["avg_test_acc"], 4),
        "spread_pct": round(one_peer["spread_pct"], 2),
        "samples_per_sec": round(one_peer["samples_per_sec"], 1),
        "host_gap_pct": round(one_peer["host_gap_pct"], 2),
        "bytes_on_wire": one_peer["bytes_on_wire"],
        "dense_bytes_on_wire": dense["bytes_on_wire"],
    }


def _population_config(*, clients: int, cohort: int, train_size: int,
                       test_size: int, local_ep: int | None = None,
                       model: str | None = None, prefetch: str = "off"):
    """The client-scale leg: baseline3 (FedAvg, 16 non-IID MNIST
    shards, model1) with the worker==lane equation broken — a
    ``clients``-record registry sampling a ``cohort`` each round onto
    the 16 lanes in ceil(cohort/16) waves with hierarchical (bucketed
    reduce-scatter) aggregation (dopt.population).  ``model`` swaps the
    headline model1 CNN for a lighter one (the --quick CI mode runs the
    mlp — same registry/wave/reduce machinery end to end, CPU-viable
    FLOPs; the chaos quick leg set the precedent)."""
    import dataclasses

    from dopt.config import PopulationConfig
    from dopt.presets import baseline_3_fedavg_noniid

    cfg = baseline_3_fedavg_noniid()
    data = dataclasses.replace(cfg.data, synthetic_train_size=train_size,
                               synthetic_test_size=test_size,
                               plan_impl="native")
    fed = dataclasses.replace(cfg.federated, prefetch=prefetch)
    if local_ep is not None:
        fed = dataclasses.replace(fed, local_ep=local_ep)
    mdl = cfg.model
    if model is not None:
        mdl = dataclasses.replace(mdl, model=model, faithful=False)
    return dataclasses.replace(
        cfg, name=f"bench-baseline3-xclients-{clients}", data=data,
        federated=fed, model=mdl,
        population=PopulationConfig(clients=clients, cohort=cohort))


def _measure_population(*, clients: int, cohort: int, train_size: int,
                        test_size: int, rounds: int, repeats: int,
                        local_ep: int | None = None,
                        model: str | None = None, telemetry=None,
                        prefetch: str = "off") -> dict:
    """Client-scale throughput: rounds/sec of the population wave loop
    and the headline ``clients_per_sec`` = cohort · rounds/sec (how many
    client visits the trainer serves per second).  The federated engine
    evaluates the global model every round (the reference's cadence),
    so — unlike the gossip legs — eval is part of the measured round;
    the JSON notes it.  The wall reduction mirrors ``_measure``
    (min/max-trimmed median over independent blocks)."""
    import jax

    from dopt.engine.federated import FederatedTrainer

    cfg = _population_config(clients=clients, cohort=cohort,
                             train_size=train_size, test_size=test_size,
                             local_ep=local_ep, model=model,
                             prefetch=prefetch)
    trainer = FederatedTrainer(cfg, eval_train=False)
    if telemetry is not None:
        from dopt.obs import attach

        attach(trainer, telemetry, fresh=True)
    trainer.run(rounds=1)   # warmup: compiles the wave-scan round
    rps = []
    total = 0.0
    for _ in range(repeats):
        t0 = time.time()
        trainer.run(rounds=rounds)
        jax.block_until_ready(trainer.theta)
        elapsed = time.time() - t0
        total += elapsed
        rps.append(rounds / elapsed)
    med, spread, _ = _trimmed_stats(rps)
    reg = trainer._registry
    last = trainer.history.rows[-1]
    plan_s = trainer.timers.totals.get("host_batch_plan", 0.0)
    step_s = trainer.timers.totals.get("round_step", 0.0)
    plan_frac = plan_s / (plan_s + step_s) if plan_s + step_s > 0 else 0.0
    if telemetry is not None:
        # The clients/sec headline flows through the same emitter the
        # engines use, next to the population run's round events.
        telemetry.emit("gauge", round=max(trainer.round - 1, 0),
                       name=f"clients_per_sec_{clients}",
                       value=med * reg.cohort_size)
        telemetry.emit("gauge", round=max(trainer.round - 1, 0),
                       name="host_batch_plan_fraction", value=plan_frac)
    return {
        "metric": "clients_per_sec_baseline3_xclients",
        "value": round(med * reg.cohort_size, 2),
        "unit": "clients/sec",
        "clients_per_sec": round(med * reg.cohort_size, 2),
        "model": cfg.model.model,
        "population": reg.clients,
        "cohort_size": reg.cohort_size,
        "waves": reg.waves,
        "lanes": reg.lanes,
        "rounds_per_sec": round(med, 4),
        "spread_pct": round(spread, 2),
        "measured_seconds": round(total, 2),
        "prefetch": prefetch,
        "host_gap_pct": round(100.0 * plan_frac, 2),
        "host_batch_plan_fraction": round(plan_frac, 4),
        "eval_fused": True,
        "final_test_acc": round(float(last["test_acc"]), 4),
        "total_trained_rounds": trainer.round,
    }


def _trimmed_stats(values):
    """Shared with scripts/bench_seqlm.py — see
    ``dopt.utils.metrics.trimmed_stats``."""
    from dopt.utils.metrics import trimmed_stats

    return trimmed_stats(values)


def _bytes_on_wire(cfg) -> float:
    """Per-round collective bytes of ``cfg``'s compiled round program
    (``hlo_collective_bytes`` over ``lower_round``'s compiled HLO) — the
    bytes-on-wire headline every bench leg now carries.  Probed on a
    THROWAWAY trainer: ``lower_round`` consumes the run loop's stateful
    host draws, so probing the measured trainer would shift its fault /
    sampling streams.  On a 1-device mesh collectives compile away and
    the honest answer is 0.0; any probe failure degrades to 0.0 with a
    note rather than taking down the wall-clock benchmark."""
    try:
        from dopt.engine import GossipTrainer
        from dopt.parallel.collectives import hlo_collective_bytes

        probe = GossipTrainer(cfg, eval_every=1 << 20)
        _, lowered = probe.lower_round()
        return float(hlo_collective_bytes(lowered.compile().as_text())
                     ["total"])
    except Exception as e:  # pragma: no cover - environment-dependent
        print(f"# bytes-on-wire probe unavailable: {e!r}", file=sys.stderr)
        return 0.0


def _measure(cfg, rounds: int, block: int, repeats: int = 5,
             device_blocks: int = 0, max_spread: float = 0.0,
             max_retries: int = 2, telemetry=None):
    """Warm up (compile), then time ``repeats`` independent blocks of
    ``rounds`` rounds each and reduce via ``_trimmed_stats`` — the
    tunneled chip shows ±8-27% wall-clock variance on identical code
    (VERDICT r3/r5), so a single window makes round-over-round
    comparisons noise-limited and untrimmed spreads are stall-poisoned.
    ``max_spread`` > 0 arms the retry gate: while the trimmed spread
    exceeds it (and retries remain), ``repeats`` more blocks are timed
    and the reduction re-runs over ALL samples.  Evaluation stays OUT
    of the measured loop (eval is a metric, not the workload; the
    reference times its rounds the same way).

    ``device_blocks`` > 0 additionally runs that many profiler-traced
    blocks and reports DEVICE-self-time rounds/sec — the tunnel-immune
    basis — plus the conv/comm/update phase fractions of device time
    (``dopt.utils.profiling.phase_totals`` over the trace).

    Returns a dict: rounds/sec (trimmed median), spread_pct (trimmed)
    + spread_pct_raw, wall_retries/measured_blocks_total, post-run avg
    test acc, total measured seconds, samples/sec, total trained
    rounds, and — when traced — device_ms_per_round + device-basis
    rounds/sec + spread + phase_fractions.
    """
    import statistics

    from dopt.engine import GossipTrainer

    # eval_every > total rounds dispatched => the measured block carries
    # zero eval steps (lax.cond skips the branch's work at runtime).
    total_dispatch = rounds * (repeats * (1 + max_retries)
                               + device_blocks + 2)
    trainer = GossipTrainer(cfg, eval_every=10 * total_dispatch + 97)
    if telemetry is not None:
        # Round/fault/gauge events + host spans for every measured
        # block flow through the shared emitter (dopt.obs); `fresh`
        # starts a new stream segment for this leg.
        from dopt.obs import attach

        attach(trainer, telemetry, fresh=True)
    # Warmup: compile the fused block step for every block size the
    # measured loop will dispatch (the remainder block retraces).
    trainer.run(rounds=block, block=block)
    trained = block
    if rounds % block:
        trainer.run(rounds=rounds % block, block=block)
        trained += rounds % block
    import jax

    rps = []
    total = 0.0

    def time_blocks(n):
        nonlocal total, trained
        for _ in range(n):
            t0 = time.time()
            trainer.run(rounds=rounds, block=block)
            jax.block_until_ready(trainer.params)
            elapsed = time.time() - t0
            total += elapsed
            rps.append(rounds / elapsed)
            trained += rounds

    time_blocks(repeats)
    med, spread, _ = _trimmed_stats(rps)
    retries = 0
    while max_spread > 0 and spread > max_spread and retries < max_retries:
        # The wall number is meaningless at this spread — buy more
        # samples and re-reduce (the gate the 27.4% r5 spread demanded).
        retries += 1
        print(f"# wall spread {spread:.1f}% > {max_spread:.1f}%: retry "
              f"{retries}/{max_retries} with {repeats} more blocks",
              file=sys.stderr)
        time_blocks(repeats)
        med, spread, _ = _trimmed_stats(rps)
    samples_per_round = (trainer.num_workers * cfg.gossip.local_ep
                         * trainer._train_matrix.shape[1])
    out = {
        "rounds_per_sec": med,
        "spread_pct": spread,
        "spread_pct_raw": (100.0 * (max(rps) - min(rps))
                           / statistics.median(rps)),
        "wall_retries": retries,
        "measured_blocks_total": len(rps),
        "measured_seconds": total,
        "samples_per_sec": med * samples_per_round,
    }
    if device_blocks:
        try:
            from dopt.utils.profiling import PHASES, device_stats_of

            def one_block():
                # Count INSIDE the block: rounds trained before a
                # device_stats_of failure partway through still reflect
                # in fast_total_trained_rounds (the accuracy column's
                # denominator must match what actually ran).
                nonlocal trained
                trainer.run(rounds=rounds, block=block)
                jax.block_until_ready(trainer.params)
                trained += rounds

            dev_us, phase_us = [], {k: 0.0 for k in PHASES}
            for _ in range(device_blocks):
                stats = device_stats_of(one_block, telemetry=telemetry)
                if stats.get("warning"):
                    # Graceful profiler degrade (dopt.utils.profiling):
                    # the block still TRAINED (counted above); drop the
                    # device basis rather than report NaN medians.
                    print(f"# device-time basis degraded: "
                          f"{stats['warning']}", file=sys.stderr)
                    dev_us = []
                    break
                dev_us.append(stats["device_self_time_us"])
                ph = stats.get("device_phases", {})
                for k in PHASES:
                    phase_us[k] += float(ph.get(f"{k}_us", 0.0))
            if dev_us:
                dev_ms = statistics.median(dev_us) / 1e3 / rounds
                out["device_ms_per_round"] = dev_ms
                out["device_rounds_per_sec"] = 1e3 / dev_ms
                out["device_spread_pct"] = (100.0
                                            * (max(dev_us) - min(dev_us))
                                            / statistics.median(dev_us))
            tot_us = sum(phase_us.values())
            if tot_us > 0:
                # Conv / mixing-comm / update split of device time over
                # all traced blocks (named-scope + op-category
                # attribution, dopt.utils.profiling.classify_phase).
                out["phase_fractions"] = {
                    k: round(v / tot_us, 4) for k, v in phase_us.items()}
                if telemetry is not None:
                    telemetry.emit(
                        "phase", round=max(trainer.round - 1, 0),
                        fractions=out["phase_fractions"],
                        device_ms_per_round=out.get("device_ms_per_round"))
        except Exception as e:  # pragma: no cover - environment-dependent
            # The device-time basis needs the profiler + xprof stack;
            # its absence (or a tunnel hiccup) must not take down the
            # wall-clock benchmark the driver records.
            print(f"# device-time basis unavailable: {e!r}",
                  file=sys.stderr)
    # Host-gap accounting (ROADMAP lever 2, the prefetch PR's measured
    # claim): how much of the wall the host pipeline costs.  Primary
    # basis: device vs wall rounds/sec (tunnel-immune, from the traced
    # blocks); fallback when no device basis ran (--quick, smoke, a
    # degraded profiler): the host-timer estimate — the
    # host_batch_plan share of the measured phases.  Always finite.
    plan_s = trainer.timers.totals.get("host_batch_plan", 0.0)
    step_s = trainer.timers.totals.get("round_step", 0.0)
    plan_frac = plan_s / (plan_s + step_s) if plan_s + step_s > 0 else 0.0
    out["host_batch_plan_fraction"] = plan_frac
    if "device_rounds_per_sec" in out:
        out["host_gap_pct"] = 100.0 * (
            1.0 - out["rounds_per_sec"] / out["device_rounds_per_sec"])
    else:
        out["host_gap_pct"] = 100.0 * plan_frac
    if telemetry is not None:
        r = max(trainer.round - 1, 0)
        telemetry.emit("gauge", round=r, name="host_gap_pct",
                       value=float(out["host_gap_pct"]))
        telemetry.emit("gauge", round=r, name="host_batch_plan_fraction",
                       value=float(plan_frac))
    # Bytes-on-wire is a first-class column of every measured leg: the
    # compiled round program's collective bytes (0.0 on a 1-device
    # mesh, where there IS no wire).
    out["bytes_on_wire"] = _bytes_on_wire(cfg)
    if telemetry is not None:
        telemetry.emit("gauge", round=max(trainer.round - 1, 0),
                       name="bytes_on_wire", value=out["bytes_on_wire"])
    # Post-run accuracy reflects ALL rounds trained above (ADVICE r4):
    # the count is recorded so the accuracy column is interpretable.
    out["total_trained_rounds"] = trained
    out["avg_test_acc"] = float(trainer.evaluate()["acc"].mean())
    return out


def _measure_fused(*, train_size: int, test_size: int, rounds: int,
                   block: int, repeats: int, faithful_model: bool = True,
                   prefetch: str = "off", max_spread: float = 0.0,
                   telemetry=None):
    """Fused-epilogue A/B on the fast workload: the identical bf16 leg
    measured with ``GossipConfig.fused_update`` off and on, both with
    ``update_sharding='off'`` — the fused epilogue replaces the dense
    consensus contraction, and the scatter path is one of its
    documented non-compositions (the eligibility matrix row).  The off
    leg compiles the exact pre-change oracle-parity program; the on leg
    runs the one-pass ``fused_mix_update`` Pallas epilogue over the
    restructured (post-mix params, displacement) scan carry.  Returns
    both rounds/sec plus their ratio (``fused_speedup``) and the fused
    leg's accuracy — the headline fields the regress ledger tracks."""
    base = _measure(
        _config(fast=True, train_size=train_size, test_size=test_size,
                faithful_model=faithful_model, update_sharding="off",
                prefetch=prefetch, fused="off"),
        rounds, block, repeats, max_spread=max_spread, telemetry=telemetry)
    fused = _measure(
        _config(fast=True, train_size=train_size, test_size=test_size,
                faithful_model=faithful_model, update_sharding="off",
                prefetch=prefetch, fused="on"),
        rounds, block, repeats, max_spread=max_spread, telemetry=telemetry)
    return {
        "fused_rounds_per_sec": round(fused["rounds_per_sec"], 4),
        "fused_off_rounds_per_sec": round(base["rounds_per_sec"], 4),
        "fused_speedup": round(fused["rounds_per_sec"]
                               / base["rounds_per_sec"], 4),
        "fused_spread_pct": round(fused["spread_pct"], 2),
        "fused_avg_test_acc": round(fused["avg_test_acc"], 4),
    }


def _fused_modes_config(*, fused: str, train_size: int, test_size: int,
                        workers: int = 6, rounds: int = 8,
                        prefetch: str = "off"):
    """The r07 standalone fused-ablation workload: the hbm-reuse-gate
    shape (6 worker lanes, MLP, circle topology, metropolis weights)
    at ledger size.  MLP rather than model1 so the leg is feasible on
    every backend the ledger sees — model1's grouped conv stack is
    accelerator-bound, and the fused epilogue's cost model (one pass
    over the params instead of mix-then-axpy) is architecture-agnostic;
    the model1 delta rides the full bench's ``--fused`` leg instead."""
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)

    return ExperimentConfig(
        name=f"bench-fused-{fused}",
        seed=2029,
        data=DataConfig(dataset="synthetic", num_users=workers, iid=True,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size,
                        plan_impl="native"),
        model=ModelConfig(model="mlp", faithful=False,
                          compute_dtype="bfloat16"),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=rounds,
                            local_ep=1, local_bs=128, prefetch=prefetch,
                            fused_update=fused),
    )


def _measure_fused_modes(*, train_size: int, test_size: int, rounds: int,
                         repeats: int, workers: int = 6, telemetry=None,
                         prefetch: str = "off", max_spread: float = 0.0,
                         hbm_rounds: int | None = 8) -> dict:
    """Standalone r07 mode: the fused-epilogue A/B on the MLP gossip
    workload, under its own ledger key (same pattern as the r06
    ``--topology-modes`` leg — a different workload from the model1
    headline, so the ``(metric, device_kind)`` key keeps the windows
    separate).  Two legs of the identical blocked run differing ONLY
    in ``GossipConfig.fused_update``; the headline ``value`` is the
    fused leg's rounds/sec, with the off leg and their ratio
    (``fused_speedup``) alongside.  When ``hbm_rounds`` is set the
    donation proof (block=1 vs block=4 subprocess peaks) is folded
    into the same entry, so one ledger line carries fused throughput,
    the speedup, and the HBM-reuse evidence."""
    kind, _ = _device_peak_flops()
    legs = {}
    for name in ("off", "on"):
        legs[name] = _measure(
            _fused_modes_config(fused=name, train_size=train_size,
                                test_size=test_size, workers=workers,
                                rounds=rounds, prefetch=prefetch),
            rounds, rounds, repeats, max_spread=max_spread,
            telemetry=telemetry)
        print(f"# fused-modes {name}: "
              f"{legs[name]['rounds_per_sec']:.4f} r/s (spread "
              f"{legs[name]['spread_pct']:.1f}%, "
              f"acc={legs[name]['avg_test_acc']:.4f})", file=sys.stderr)
    base, fused = legs["off"], legs["on"]
    result = {
        "metric": f"gossip_fused_epilogue_dsgd_mlp_{workers}workers",
        "value": round(fused["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "workers": workers,
        "rounds_per_block": rounds,
        "device_kind": kind,
        "prefetch": prefetch,
        "fused_rounds_per_sec": round(fused["rounds_per_sec"], 4),
        "fused_off_rounds_per_sec": round(base["rounds_per_sec"], 4),
        "fused_speedup": round(fused["rounds_per_sec"]
                               / base["rounds_per_sec"], 4),
        "fused_spread_pct": round(fused["spread_pct"], 2),
        "fused_avg_test_acc": round(fused["avg_test_acc"], 4),
        "fused_off_avg_test_acc": round(base["avg_test_acc"], 4),
        "spread_pct": round(fused["spread_pct"], 2),
        "samples_per_sec": round(fused["samples_per_sec"], 1),
        "host_gap_pct": round(fused["host_gap_pct"], 2),
        "bytes_on_wire": fused["bytes_on_wire"],
    }
    if hbm_rounds:
        hbm = _hbm_reuse_measure(rounds=hbm_rounds)
        result["hbm_reuse_status"] = hbm["status"]
        for key in ("hbm_peak_bytes_block1", "hbm_peak_bytes_block4",
                    "growth_pct", "hbm_source"):
            if key in hbm:
                result["hbm_reuse_" + key.removeprefix("hbm_")] = hbm[key]
    return result


def _measure_comm_modes(*, train_size: int, test_size: int, rounds: int,
                        repeats: int, workers: int = 8,
                        conv_rounds: int = 24, probe_devices: int = 4,
                        telemetry=None, max_spread: float = 0.0) -> dict:
    """Standalone r08 mode: the comm-substrate codec headline under its
    own ledger key (the r06/r07 standalone-workload pattern).

    Three measured bases, one entry:

    * **bytes on wire** — the compiled-HLO collective bytes of the
      dense / raw-scatter / codec round programs, probed in a
      subprocess (``python -m dopt.analysis.comm_bytes``) so the
      multi-device host mesh can be forced before jax init when the
      bench itself runs on a 1-device CPU backend.  The headline
      ``wire_compression`` is dense/codec — gather-vs-gather, the fair
      op-kind pairing (module docstring there).
    * **throughput** — ``_measure`` on the raw-scatter and codec legs
      (identical workload, fault-free); ``value`` is the codec leg's
      rounds/sec (``compressed_rounds_per_sec`` in the regress ledger:
      the codec must not buy its bytes with a dispatch-bound round).
    * **rounds to target** — both legs re-run with the lossy preset's
      crash + churn cocktail armed (its ``msg_*`` knobs price the byte
      budget instead — they run the per-staleness link engine, a
      different wire) for ``conv_rounds`` blocked rounds; the target is
      the raw leg's final train loss × 1.02 and each leg reports the
      first round that reaches it, so the ledger shows the compression
      schedule still trains, not just that it shrinks the wire."""
    import subprocess

    from dopt.analysis.comm_bytes import (comm_modes_config,
                                          lossy_budget_bytes)

    kind, _ = _device_peak_flops()
    probe = None
    cmd = [sys.executable, "-m", "dopt.analysis.comm_bytes",
           "--workers", str(workers), "--devices", str(probe_devices),
           "--train-size", str(train_size), "--test-size", str(test_size)]
    try:
        run = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=1_200, cwd=os.path.dirname(
                                 os.path.abspath(__file__)))
        if run.returncode == 0:
            probe = json.loads(run.stdout.strip().splitlines()[-1])
        else:
            print(f"# comm-bytes probe rc={run.returncode}: "
                  f"{run.stderr.strip().splitlines()[-1:]}",
                  file=sys.stderr)
    except Exception as e:  # pragma: no cover - environment-dependent
        print(f"# comm-bytes probe unavailable: {e!r}", file=sys.stderr)
    if probe is not None:
        budget = int(probe["budget_bytes"])
    else:
        # Fallback budget derivation (the CLI's own path), in-process:
        # spec widths are device-count independent.
        from dopt.engine import GossipTrainer

        tr = GossipTrainer(
            comm_modes_config("scatter", workers=workers,
                              train_size=train_size, test_size=test_size),
            eval_every=1 << 20)
        dense_bytes = (tr._scatter_spec.bounds[-1]
                       - tr._scatter_spec.bounds[0]) * 4
        budget = lossy_budget_bytes(dense_bytes, workers)
        del tr
    budget_mb = budget / (1 << 20)

    legs = {}
    for name in ("scatter", "codec"):
        legs[name] = _measure(
            comm_modes_config(name, workers=workers,
                              train_size=train_size, test_size=test_size,
                              rounds=rounds, budget_mb=budget_mb),
            rounds, rounds, repeats, max_spread=max_spread,
            telemetry=telemetry)
        print(f"# comm-modes {name}: "
              f"{legs[name]['rounds_per_sec']:.4f} r/s (spread "
              f"{legs[name]['spread_pct']:.1f}%, "
              f"acc={legs[name]['avg_test_acc']:.4f})", file=sys.stderr)

    def _converge(mode):
        from dopt.engine import GossipTrainer

        cfg = comm_modes_config(mode, workers=workers,
                                train_size=train_size,
                                test_size=test_size, rounds=conv_rounds,
                                budget_mb=budget_mb, faults=True)
        tr = GossipTrainer(cfg, eval_every=max(conv_rounds // 2, 1))
        tr.run(rounds=conv_rounds, block=conv_rounds)
        return [float(r["avg_train_loss"]) for r in tr.history.rows]

    raw_losses = _converge("scatter")
    codec_losses = _converge("codec")
    target = raw_losses[-1] * 1.02

    def _rounds_to(losses):
        for i, v in enumerate(losses):
            if v <= target:
                return i + 1
        return len(losses)

    raw, codec = legs["scatter"], legs["codec"]
    result = {
        "metric": f"gossip_comm_codec_dsgd_mlp_{workers}workers",
        "value": round(codec["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "workers": workers,
        "rounds_per_block": rounds,
        "device_kind": kind,
        "compressed_rounds_per_sec": round(codec["rounds_per_sec"], 4),
        "raw_scatter_rounds_per_sec": round(raw["rounds_per_sec"], 4),
        "codec_overhead_pct": round(
            100.0 * (1.0 - codec["rounds_per_sec"]
                     / raw["rounds_per_sec"]), 2),
        "budget_bytes": int(budget),
        "target_avg_train_loss": round(target, 4),
        "rounds_to_target_raw": _rounds_to(raw_losses),
        "rounds_to_target_codec": _rounds_to(codec_losses),
        "raw_final_train_loss": round(raw_losses[-1], 4),
        "codec_final_train_loss": round(codec_losses[-1], 4),
        "conv_rounds": conv_rounds,
        "codec_avg_test_acc": round(codec["avg_test_acc"], 4),
        "raw_avg_test_acc": round(raw["avg_test_acc"], 4),
        "spread_pct": round(codec["spread_pct"], 2),
        "samples_per_sec": round(codec["samples_per_sec"], 1),
        "host_gap_pct": round(codec["host_gap_pct"], 2),
    }
    if probe is not None:
        result.update({
            "bytes_on_wire": float(probe["codec"]["total"]),
            "dense_bytes_on_wire": float(probe["dense"]["total"]),
            "scatter_bytes_on_wire": float(probe["scatter"]["total"]),
            "wire_compression": probe["wire_compression"],
            "plan_kinds": ",".join(probe["plan_kinds"]),
            "plan_compression": probe["plan_compression"],
            "probe_devices": probe["devices"],
            "codec_bytes_by_dtype": probe["codec"]["by_dtype"],
        })
    else:
        # Degraded basis: the in-process probe (0.0 on a 1-device
        # mesh) plus the schedule's analytic compression — present and
        # finite either way, flagged so a ledger reader knows which
        # basis this row carries.
        result["bytes_on_wire"] = codec["bytes_on_wire"]
        result["probe_devices"] = 0
    return result


def _measure_seqlm(*, steps: int, seq_len: int, batch: int, repeats: int,
                   kv_chunk: int = 0, telemetry=None):
    """The seqlm headline leg (promoted from ``scripts/bench_seqlm.py``,
    which stays the standalone sweep tool): steady-state tokens/sec of
    the ``seqlm`` preset — decoder-only TransformerLM, ring attention,
    sequence axis sharded over all devices.  Emits the standard
    bench-line schema so the ledger judges it under its OWN
    ``(seqlm_tokens_per_sec, device_kind)`` key, separate from the
    gossip headline windows."""
    import dataclasses

    import jax

    from dopt.engine import SeqLMTrainer
    from dopt.presets import get_preset

    cfg = get_preset("seqlm")
    cfg = cfg.replace(seqlm=dataclasses.replace(
        cfg.seqlm, steps=steps, seq_len=seq_len, batch=batch,
        kv_chunk=kv_chunk, log_every=max(steps // 3, 1)))
    tr = SeqLMTrainer(cfg)
    tr.run(steps=3)                       # compile + warmup
    tokens = steps * batch * seq_len
    tps, total = [], 0.0
    for _ in range(max(repeats, 1)):
        t0 = time.time()
        tr.run(steps=steps)
        jax.block_until_ready(tr.params)
        elapsed = time.time() - t0
        total += elapsed
        tps.append(tokens / elapsed)
    med, spread, _ = _trimmed_stats(tps)
    out = {
        "metric": "seqlm_tokens_per_sec",
        "value": round(med, 1),
        "unit": "tokens/sec",
        "device_kind": str(jax.devices()[0].device_kind),
        "spread_pct": round(spread, 2),
        "measured_windows": len(tps),
        "measured_seconds": round(total, 2),
        "steps_per_window": steps,
        "attn": cfg.seqlm.attn,
        "seq_len": seq_len,
        "batch": batch,
        "kv_chunk": kv_chunk,
        "mesh_devices": tr.mesh.size,
        "params": tr.param_count,
        "final_loss": round(tr.history.last()["loss"], 4),
    }
    from dopt.utils.profiling import device_memory_stats

    mem = device_memory_stats()
    if mem is not None:
        out["hbm_peak_gb"] = round(mem["peak_bytes"] / 2**30, 3)
        out["hbm_source"] = mem["source"]
    if telemetry is not None:
        from dopt.obs.events import sanitize_metrics

        telemetry.emit("bench", metrics=sanitize_metrics(out))
    return out


def _hbm_reuse_point(block: int, rounds: int) -> None:
    """(internal, spawned by ``--hbm-reuse-check``) Run the fused
    gossip workload at ONE block size in THIS process and print its
    peak-memory gauge — per-process peaks are comparable; a shared
    process would see only the running maximum."""
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer
    from dopt.utils.profiling import device_memory_stats

    import jax

    cfg = ExperimentConfig(
        name=f"hbm-reuse-b{block}", seed=7,
        data=DataConfig(dataset="synthetic", num_users=6,
                        synthetic_train_size=1_536,
                        synthetic_test_size=256),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=rounds,
                            local_ep=1, local_bs=128,
                            fused_update="on"))
    tr = GossipTrainer(cfg, eval_every=10 * rounds + 97)
    tr.run(rounds=rounds, block=block)
    jax.block_until_ready(tr.params)
    mem = device_memory_stats()
    print(json.dumps({
        "block": block,
        "hbm_peak_bytes": None if mem is None else int(mem["peak_bytes"]),
        "hbm_source": None if mem is None else mem["source"],
    }))


def _hbm_reuse_measure(*, rounds: int = 8,
                       tolerance_pct: float = 10.0) -> dict:
    """Measure the donation proof: run the fused-epilogue workload
    per-round (block=1) and blocked (block=4), each in its OWN
    subprocess (per-process peaks are comparable; a shared process
    would see only the running maximum), and compare the peak-memory
    gauges.  Returns the verdict dict — ``status`` is ``ok`` when the
    block=4 peak is flat to ±``tolerance_pct``, ``FAIL`` on growth or
    a failed point run, ``skipped`` when the backend has no gauge."""
    import subprocess

    peaks, src = {}, None
    for block in (1, 4):
        cmd = [sys.executable, __file__, "--hbm-reuse-point", str(block),
               "--rounds", str(rounds)]
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=900)
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")), None)
        if r.returncode != 0 or line is None:
            return {"check": "hbm_reuse", "status": "FAIL",
                    "reason": f"block={block} point run failed",
                    "stderr_tail": r.stderr.strip()[-400:]}
        p = json.loads(line)
        if p["hbm_peak_bytes"] is None:
            return {"check": "hbm_reuse", "status": "skipped",
                    "reason": "no memory gauge on this backend"}
        peaks[block] = int(p["hbm_peak_bytes"])
        src = p["hbm_source"]
    growth = 100.0 * (peaks[4] - peaks[1]) / peaks[1]
    return {
        "check": "hbm_reuse",
        "status": "ok" if growth <= tolerance_pct else "FAIL",
        "hbm_peak_bytes_block1": peaks[1],
        "hbm_peak_bytes_block4": peaks[4],
        "growth_pct": round(growth, 2),
        "tolerance_pct": tolerance_pct,
        "rounds": rounds,
        "hbm_source": src,
    }


def _hbm_reuse_check(*, rounds: int = 8, tolerance_pct: float = 10.0) -> int:
    """The donation proof the CI quick job asserts (hbm-reuse gate):
    peak bytes must not scale with block length.  Round-carry donation
    through the blocked ``lax.scan`` (params/momentum/displacement
    donated into each round and each block dispatch) is what keeps the
    blocked program at one resident carry; a donation regression shows
    up here as the block=4 peak growing past the gate.  Prints one
    JSON verdict line; returns a process exit code (0 flat/skipped,
    1 regressed/failed)."""
    res = _hbm_reuse_measure(rounds=rounds, tolerance_pct=tolerance_pct)
    print(json.dumps(res))
    return 0 if res["status"] in ("ok", "skipped") else 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny data / few rounds (CI smoke, not a benchmark)")
    ap.add_argument("--quick", action="store_true",
                    help="chaos-metric-only quick run (tiny data, few "
                         "rounds): prints the gossip_rounds_per_sec_chaos "
                         "JSON line and exits — the CI artifact mode")
    ap.add_argument("--skip-chaos", action="store_true",
                    help="skip the chaos-cocktail (degraded-network) leg")
    ap.add_argument("--skip-clients", action="store_true",
                    help="skip the client-scale (population registry) legs")
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--block", type=int, default=None,
                    help="rounds fused per jit dispatch (default: all "
                         "measured rounds in one fused lax.scan block)")
    ap.add_argument("--skip-faithful", action="store_true",
                    help="measure only the fast (bf16) mode")
    ap.add_argument("--repeats", type=int, default=5,
                    help="independent measured blocks; the reported value "
                         "is their min/max-trimmed median (variance "
                         "hardening: the tunneled chip shows ±8-27%% "
                         "single-window wall-clock noise)")
    ap.add_argument("--max-spread", type=float, default=10.0,
                    help="wall-spread gate (%%): while the trimmed "
                         "per-block rounds/sec spread exceeds this, the "
                         "measurement retries with --repeats more blocks "
                         "(up to 2 retries); 0 disables the gate")
    ap.add_argument("--update-sharding", choices=("off", "scatter"),
                    default="scatter",
                    help="fast-leg consensus/update execution mode "
                         "(GossipConfig.update_sharding): 'scatter' runs "
                         "the bucketed reduce-scatter hot path with the "
                         "XLA latency-hiding scheduler armed; the "
                         "faithful f32 leg always runs 'off' (the "
                         "oracle-parity program)")
    ap.add_argument("--prefetch", choices=("on", "off"), default="on",
                    help="host-pipeline prefetch (GossipConfig/"
                         "FederatedConfig.prefetch) on the fast, chaos "
                         "and client-scale legs: block b+1's batch "
                         "plans are built + staged to device while "
                         "block b runs (dopt.data.prefetch) — the "
                         "ROADMAP lever-2 overlap; bit-identical to "
                         "'off' by construction.  The faithful f32 leg "
                         "always runs 'off' (the oracle-parity host "
                         "loop)")
    ap.add_argument("--fused", choices=("on", "off"), default="on",
                    help="measure the fused-epilogue A/B leg (the fast "
                         "workload with GossipConfig.fused_update off vs "
                         "on, both update_sharding='off'): emits "
                         "fused_rounds_per_sec + fused_speedup into the "
                         "headline JSON line and the --quick CI "
                         "artifact; 'off' skips the pair")
    ap.add_argument("--skip-seqlm", action="store_true",
                    help="skip the seqlm headline leg (ring-attention "
                         "TransformerLM tokens/sec — its own JSON line "
                         "and its own (metric, device_kind) ledger key)")
    ap.add_argument("--seqlm-steps", type=int, default=None,
                    help="seqlm leg: steps per measured window "
                         "(default 30, smoke 4)")
    ap.add_argument("--seqlm-seq-len", type=int, default=None,
                    help="seqlm leg: sequence length "
                         "(default 2048, smoke 256)")
    ap.add_argument("--hbm-reuse-check", action="store_true",
                    help="run ONLY the donation proof: fused workload "
                         "at block=1 vs block=4 (subprocess each), "
                         "assert peak memory flat to +-10%% — exits "
                         "nonzero on growth (the CI hbm-reuse gate)")
    ap.add_argument("--hbm-reuse-point", type=int, default=None,
                    metavar="BLOCK",
                    help="(internal) one --hbm-reuse-check subprocess "
                         "point: run the fused workload at this block "
                         "size and print the peak-memory gauge")
    ap.add_argument("--skip-diagnostics", action="store_true",
                    help="skip the diagnostics-overhead leg (the fast "
                         "workload re-measured with GossipConfig."
                         "diagnostics='on'; its rounds/sec and overhead "
                         "pct land in the headline JSON line — the "
                         "acceptance bar is < 5%% vs diagnostics-off)")
    ap.add_argument("--device-blocks", type=int, default=3,
                    help="profiler-traced blocks for the device-time-basis "
                         "rounds/sec (tunnel-immune; 0 disables)")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="stream structured telemetry (dopt.obs JSONL) "
                         "here: the measured legs' per-round events plus "
                         "'phase' (device-time fractions), "
                         "'gauge' (clients_per_sec) and a final 'bench' "
                         "event carrying the headline JSON line; "
                         "validate with 'python -m dopt.obs.check PATH'")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome-trace JSON of the measured "
                         "blocks' host spans here (dopt.obs span tracer)")
    ap.add_argument("--history-out", default="results/bench_history.jsonl",
                    metavar="PATH",
                    help="append the headline JSON line (stamped with "
                         "git sha + run id) to this perf-regression "
                         "ledger (dopt.obs.regress; compare runs with "
                         "'python -m dopt.obs.regress PATH'); '' "
                         "disables.  --quick and --smoke runs never "
                         "append (tiny-data values would poison the "
                         "trailing medians) — CI judges the quick "
                         "artifact via 'dopt.obs.regress --candidate' "
                         "instead")
    ap.add_argument("--topology-modes", action="store_true",
                    help="run ONLY the r06 mixing-pattern ablation "
                         "(dense vs one_peer_exp vs async at n=32) and "
                         "append its own headline to the history ledger")
    ap.add_argument("--skip-topology", action="store_true",
                    help="skip the topology-modes legs in the full bench")
    ap.add_argument("--fused-modes", action="store_true",
                    help="run ONLY the r07 fused-epilogue ablation "
                         "(fused_update off vs on on the MLP gossip "
                         "workload, plus the hbm-reuse donation proof "
                         "and the seqlm leg) and append their headlines "
                         "to the history ledger")
    ap.add_argument("--comm-modes", action="store_true",
                    help="run ONLY the r08 comm-substrate ablation "
                         "(raw scatter vs the budgeted bucket codec at "
                         "n=8: compiled-HLO bytes-on-wire, throughput, "
                         "and rounds-to-target under the crash/churn "
                         "cocktail) and append its headline to the "
                         "history ledger")
    ap.add_argument("--run-id", default=None,
                    help="ledger run id for the history append "
                         "(default: derived from sha + timestamp)")
    ap.add_argument("--idiomatic", action="store_true",
                    help="benchmark the idiomatic model head (post-conv "
                         "ReLUs, logit head + softmax-CE — faithful=False) "
                         "instead of the reference-faithful double-softmax "
                         "architecture; same JSON fields, metric suffixed "
                         "_idiomatic")
    args = ap.parse_args()

    if args.hbm_reuse_point is not None:
        _hbm_reuse_point(args.hbm_reuse_point, args.rounds or 8)
        return
    if args.hbm_reuse_check:
        sys.exit(_hbm_reuse_check(rounds=args.rounds or 8))

    if args.update_sharding == "scatter":
        # XLA reads its flags at backend init: arm the latency-hiding
        # scheduler BEFORE the first jax use so the scatter path's
        # per-bucket collectives can overlap with compute.
        from dopt.parallel.mesh import enable_latency_hiding_scheduler

        enable_latency_hiding_scheduler()

    tele = None
    if args.metrics_out or args.trace_out:
        from dopt.obs import Telemetry

        tele = (Telemetry.to_jsonl(args.metrics_out)
                if args.metrics_out else Telemetry())

    def _finish_telemetry(result: dict | None = None) -> None:
        if tele is None:
            return
        if result is not None:
            from dopt.obs.events import sanitize_metrics

            tele.emit("bench", metrics=sanitize_metrics(result))
        tele.close()
        if args.trace_out:
            tele.write_trace(args.trace_out)
            print(f"# wrote host span trace to {args.trace_out}",
                  file=sys.stderr)
        if args.metrics_out:
            print(f"# wrote telemetry stream to {args.metrics_out}",
                  file=sys.stderr)

    if args.topology_modes:
        # Standalone r06 mode: the mixing-pattern ablation only, its
        # own metric key in the ledger (the n=32 MLP wire comparison is
        # a different workload from the model1 headline, and the
        # (metric, device_kind) ledger key keeps the windows separate).
        t_rounds = args.rounds or (3 if args.smoke else 8)
        t_repeats = 2 if args.smoke else args.repeats
        tsize, esize = (4_096, 512) if args.smoke else (16_384, 2_048)
        result = _measure_topology_modes(
            train_size=tsize, test_size=esize, rounds=t_rounds,
            repeats=t_repeats, telemetry=tele, prefetch=args.prefetch,
            max_spread=0.0 if args.smoke else args.max_spread)
        print(json.dumps(result))
        if args.history_out and not args.smoke:
            try:
                from dopt.obs.regress import append_entry

                entry = append_entry(args.history_out, result,
                                     run_id=args.run_id)
                print(f"# appended run {entry['run_id']} "
                      f"(sha {entry['git_sha'] or 'unknown'}) to "
                      f"{args.history_out}", file=sys.stderr)
            except OSError as e:
                print(f"# bench history append failed: {e}",
                      file=sys.stderr)
        _finish_telemetry(result)
        return

    if args.fused_modes:
        # Standalone r07 mode: the fused-epilogue ablation + the seqlm
        # headline only, each under its own ledger key.  Mirrors the
        # r06 --topology-modes pattern — the MLP A/B workload is
        # backend-portable, so the fused/donation/seqlm windows can be
        # seeded from any box while the model1 headline waits for a
        # real accelerator run.
        f_rounds = args.rounds or (3 if args.smoke else 8)
        f_repeats = 2 if args.smoke else args.repeats
        tsize, esize = (4_096, 512) if args.smoke else (16_384, 2_048)
        result = _measure_fused_modes(
            train_size=tsize, test_size=esize, rounds=f_rounds,
            repeats=f_repeats, telemetry=tele, prefetch=args.prefetch,
            max_spread=0.0 if args.smoke else args.max_spread,
            hbm_rounds=None if args.smoke else 8)
        print(json.dumps(result))
        seqlm = None
        if not args.skip_seqlm:
            seqlm = _measure_seqlm(
                steps=args.seqlm_steps or (4 if args.smoke else 12),
                seq_len=args.seqlm_seq_len or (256 if args.smoke else 1_024),
                batch=2 if args.smoke else 4,
                repeats=1 if args.smoke else min(args.repeats, 3),
                telemetry=tele)
            print(json.dumps(seqlm))
        if args.history_out and not args.smoke:
            try:
                from dopt.obs.regress import append_entry

                for line in filter(None, (result, seqlm)):
                    entry = append_entry(args.history_out, line,
                                         run_id=args.run_id)
                    print(f"# appended run {entry['run_id']} "
                          f"({line['metric']}) to {args.history_out}",
                          file=sys.stderr)
            except OSError as e:
                print(f"# bench history append failed: {e}",
                      file=sys.stderr)
        _finish_telemetry(result)
        return

    if args.comm_modes:
        # Standalone r08 mode: the comm-substrate codec ablation only,
        # its own ledger key (the r06/r07 pattern).  The HLO byte basis
        # rides a subprocess so the probe mesh can be multi-device even
        # when this process initialized a 1-device CPU backend.
        c_rounds = args.rounds or (3 if args.smoke else 8)
        c_repeats = 2 if args.smoke else args.repeats
        tsize, esize = (2_048, 512) if args.smoke else (8_192, 1_024)
        result = _measure_comm_modes(
            train_size=tsize, test_size=esize, rounds=c_rounds,
            repeats=c_repeats, telemetry=tele,
            conv_rounds=6 if args.smoke else 24,
            max_spread=0.0 if args.smoke else args.max_spread)
        print(json.dumps(result))
        if args.history_out and not args.smoke:
            try:
                from dopt.obs.regress import append_entry

                entry = append_entry(args.history_out, result,
                                     run_id=args.run_id)
                print(f"# appended run {entry['run_id']} "
                      f"(sha {entry['git_sha'] or 'unknown'}) to "
                      f"{args.history_out}", file=sys.stderr)
            except OSError as e:
                print(f"# bench history append failed: {e}",
                      file=sys.stderr)
        _finish_telemetry(result)
        return

    if args.quick:
        # CI-artifact mode: tiny data, two measured rounds per path —
        # enough to exercise both execution paths end to end and emit
        # the tracked JSON shape; the VALUE is only meaningful from a
        # real accelerator run (the full bench measures it properly).
        # Diagnostics ride the chaos legs so the metrics artifact
        # carries the convergence gauges + resource/compile events the
        # CI gate asserts on.
        chaos = _measure_chaos(1_536, 512, rounds=args.rounds or 2,
                               repeats=2, telemetry=tele,
                               prefetch=args.prefetch,
                               diagnostics="on")
        quick_line = {"metric": "gossip_rounds_per_sec_chaos",
                      "value": chaos["gossip_rounds_per_sec_chaos"],
                      "unit": "rounds/sec", "quick": True,
                      # The CI artifact contract: host_gap_pct present
                      # and finite even without a device-time basis
                      # (here: the host-timer estimate of the chaos
                      # blocked leg).
                      "host_gap_pct": chaos["chaos_host_gap_pct"],
                      "host_batch_plan_fraction":
                          chaos["chaos_host_batch_plan_fraction"],
                      "prefetch": args.prefetch,
                      "diagnostics": "on", **chaos}
        from dopt.utils.profiling import device_memory_stats

        mem = device_memory_stats()
        if mem is not None:
            # Finite peak HBM in the quick artifact (host RSS on the
            # CPU CI runner) — the other half of the CI gate.
            quick_line["hbm_peak_gb"] = round(mem["peak_bytes"] / 2**30, 3)
            quick_line["hbm_source"] = mem["source"]
        if args.fused == "on":
            # Fused-epilogue A/B on tiny data: both execution paths end
            # to end, so the quick artifact always carries finite
            # fused_rounds_per_sec + fused_speedup fields (the CI
            # present-and-finite assertion); the VALUES are only
            # meaningful from the full bench.
            quick_line.update(_measure_fused(
                train_size=1_536, test_size=512,
                rounds=args.rounds or 2, block=args.rounds or 2,
                repeats=2, prefetch=args.prefetch, telemetry=tele))
        print(json.dumps(quick_line))
        if not args.skip_clients:
            # Client-scale quick line: the 1k-client baseline3 cohort
            # loop end to end (sampling → 4-wave scan → hierarchical
            # reduce → registry feedback) on tiny data, one local
            # epoch — the CI artifact the full bench measures properly.
            popm = _measure_population(clients=1_000, cohort=64,
                                       train_size=1_536, test_size=512,
                                       rounds=args.rounds or 2,
                                       repeats=2, local_ep=1, model="mlp",
                                       telemetry=tele,
                                       prefetch=args.prefetch)
            print(json.dumps({**popm, "quick": True}))
            quick_line.update({f"clients_{k}": v for k, v in popm.items()
                               if isinstance(v, (int, float))})
        _finish_telemetry(quick_line)
        return

    train_size = 6_000 if args.smoke else 60_000
    test_size = 1_000 if args.smoke else 10_000
    # 20 measured rounds: one fused dispatch, ~12s — averages out the
    # ~10% run-to-run variance a 10-round window shows on this chip.
    rounds = args.rounds if args.rounds is not None else (3 if args.smoke else 20)
    if rounds <= 0:
        ap.error("--rounds must be positive")
    block = args.block if args.block is not None else rounds

    faithful_model = not args.idiomatic
    repeats = 2 if args.smoke else args.repeats
    device_blocks = 0 if args.smoke else args.device_blocks
    max_spread = 0.0 if args.smoke else args.max_spread
    fast = _measure(
        _config(fast=True, train_size=train_size, test_size=test_size,
                faithful_model=faithful_model,
                update_sharding=args.update_sharding,
                prefetch=args.prefetch),
        rounds, block, repeats, device_blocks=device_blocks,
        max_spread=max_spread, telemetry=tele)
    kind, peak = _device_peak_flops()
    fast_sps = fast["samples_per_sec"]
    result = {
        "metric": "gossip_rounds_per_sec_dsgd_mnist_6workers_model1_bf16"
                  + ("" if faithful_model else "_idiomatic"),
        "value": round(fast["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "vs_baseline": round(fast["rounds_per_sec"]
                             / REFERENCE_ROUNDS_PER_SEC, 2),
        "update_sharding": args.update_sharding,
        "prefetch": args.prefetch,
        # Host-gap headline (ROADMAP lever 2): device vs wall
        # rounds/sec when the device basis ran, else the host-timer
        # estimate — the number the prefetch overlap must close to <5%.
        "host_gap_pct": round(fast["host_gap_pct"], 2),
        "host_batch_plan_fraction": round(
            fast["host_batch_plan_fraction"], 4),
        "spread_pct": round(fast["spread_pct"], 2),
        "spread_pct_raw": round(fast["spread_pct_raw"], 2),
        "wall_retries": fast["wall_retries"],
        "measured_blocks": fast["measured_blocks_total"],
        "rounds_per_block": rounds,
        "fast_avg_test_acc": round(fast["avg_test_acc"], 4),
        "fast_total_trained_rounds": fast["total_trained_rounds"],
        "device_kind": kind,
        "samples_per_sec": round(fast_sps, 1),
        "model_tflops_per_sec": round(
            fast_sps * MODEL1_TRAIN_FLOPS_PER_SAMPLE / 1e12, 2),
        "bytes_on_wire": fast["bytes_on_wire"],
    }
    if "device_ms_per_round" in fast:
        # Tunnel-immune basis: what the chip actually spent, from the
        # profiler's device self-time over --device-blocks traced blocks.
        result["device_ms_per_round"] = round(fast["device_ms_per_round"], 2)
        result["device_rounds_per_sec"] = round(
            fast["device_rounds_per_sec"], 4)
        result["device_spread_pct"] = round(fast["device_spread_pct"], 2)
        result["device_blocks"] = device_blocks
    if "phase_fractions" in fast:
        # Conv / mixing-comm / update split of device time — the
        # measured basis for "conv fraction >= X%" claims (named-scope
        # attribution, dopt.utils.profiling.classify_phase).
        pf = fast["phase_fractions"]
        result["conv_fraction"] = pf["conv"]
        result["comm_fraction"] = pf["comm"]
        result["update_fraction"] = pf["update"]
        result["other_fraction"] = pf["other"]
    if peak:
        result["mfu_vs_bf16_peak"] = round(
            fast_sps * MODEL1_TRAIN_FLOPS_PER_SAMPLE / peak, 4)
    from dopt.utils.profiling import device_memory_stats

    mem = device_memory_stats()
    if mem is not None:
        # Peak HBM of the fast leg's process (backend allocator stats
        # on TPU/GPU, host RSS on CPU — `hbm_source` says which).
        result["hbm_peak_gb"] = round(mem["peak_bytes"] / 2**30, 3)
        result["hbm_source"] = mem["source"]
    if not args.skip_diagnostics:
        # Diagnostics-overhead leg: the IDENTICAL fast workload with
        # GossipConfig.diagnostics="on" (the on-device norm/spread/
        # consensus reductions + the packed-vector growth), so the
        # headline carries the measured cost of per-round
        # introspection.  The acceptance bar is < 5% rounds/sec.
        diag = _measure(
            _config(fast=True, train_size=train_size,
                    test_size=test_size, faithful_model=faithful_model,
                    update_sharding=args.update_sharding,
                    prefetch=args.prefetch, diagnostics="on"),
            rounds, block, repeats, max_spread=max_spread,
            telemetry=tele)
        result["diagnostics_rounds_per_sec"] = round(
            diag["rounds_per_sec"], 4)
        result["diagnostics_overhead_pct"] = round(
            100.0 * (1.0 - diag["rounds_per_sec"]
                     / fast["rounds_per_sec"]), 2)
        print(f"# diagnostics on: {diag['rounds_per_sec']:.4f} r/s vs "
              f"off {fast['rounds_per_sec']:.4f} r/s "
              f"({result['diagnostics_overhead_pct']:+.2f}% overhead)",
              file=sys.stderr)
    if args.fused == "on":
        # Fused-epilogue headline (ROADMAP raw-speed lever 3 landing):
        # the identical workload with the round epilogue as ONE
        # fused_mix_update pass vs the two-op reference — the ratio is
        # the ledger-tracked fused_speedup.
        fusedm = _measure_fused(
            train_size=train_size, test_size=test_size, rounds=rounds,
            block=block, repeats=repeats, faithful_model=faithful_model,
            prefetch=args.prefetch, max_spread=max_spread, telemetry=tele)
        result.update(fusedm)
        print(f"# fused epilogue: on {fusedm['fused_rounds_per_sec']:.4f} "
              f"r/s vs off {fusedm['fused_off_rounds_per_sec']:.4f} r/s "
              f"({fusedm['fused_speedup']:.2f}x; "
              f"acc={fusedm['fused_avg_test_acc']:.4f})", file=sys.stderr)
    if not args.skip_chaos:
        # Second headline: the degraded-network cocktail at blocked
        # (fused-scan) speed, with the pre-change per-round path timed
        # alongside so the dispatch-overhead win stays measured.
        chaos = _measure_chaos(train_size, test_size, rounds, repeats,
                               telemetry=tele, prefetch=args.prefetch)
        result.update(chaos)
        print(f"# chaos cocktail: blocked "
              f"{chaos['gossip_rounds_per_sec_chaos']:.4f} r/s vs "
              f"per-round {chaos['chaos_per_round_rounds_per_sec']:.4f} "
              f"r/s ({chaos['chaos_speedup_vs_per_round']:.2f}x; "
              f"acc={chaos['chaos_avg_test_acc']:.4f})", file=sys.stderr)
    if not args.skip_clients:
        # Client-scale headlines (dopt.population): clients/sec served
        # at population 1k (cohort 64 → 4 waves) and 10k (cohort 256 →
        # 16 waves) on baseline3 — each its own JSON line, with the
        # summary numbers folded into the main line.
        for n_clients, cohort in ((1_000, 64), (10_000, 256)):
            popm = _measure_population(
                clients=n_clients, cohort=cohort, train_size=train_size,
                test_size=test_size,
                rounds=max(rounds // 4, 2) if not args.smoke else 2,
                repeats=repeats, telemetry=tele,
                prefetch=args.prefetch)
            result[f"clients_per_sec_{n_clients // 1000}k"] = popm["value"]
            print(f"# clients/sec @ population={n_clients} "
                  f"(cohort {cohort}, {popm['waves']} waves): "
                  f"{popm['value']:.1f} "
                  f"({popm['rounds_per_sec']:.3f} rounds/s, "
                  f"acc={popm['final_test_acc']:.4f})", file=sys.stderr)
            print(json.dumps(popm))
    if not args.skip_topology:
        # r06 legs: the mixing-pattern ablation at n=32 rides the full
        # bench too (own JSON line; the ratios fold into the headline
        # so the regress ledger watches the one-peer/async wire win).
        topo = _measure_topology_modes(
            train_size=4_096 if args.smoke else 16_384,
            test_size=512 if args.smoke else 2_048,
            rounds=rounds, repeats=repeats, telemetry=tele,
            prefetch=args.prefetch, max_spread=max_spread)
        print(json.dumps(topo))
        for k in ("dense_rounds_per_sec", "one_peer_rounds_per_sec",
                  "async_rounds_per_sec", "one_peer_speedup_vs_dense",
                  "async_speedup_vs_dense", "async_speedup_vs_one_peer",
                  "one_peer_avg_test_acc", "async_avg_test_acc"):
            result[k] = topo[k]
    if not args.skip_faithful:
        faith = _measure(
            _config(fast=False, train_size=train_size, test_size=test_size,
                    faithful_model=faithful_model),
            rounds, block, repeats)
        result["faithful_f32_rounds_per_sec"] = round(
            faith["rounds_per_sec"], 4)
        result["faithful_f32_vs_baseline"] = round(
            faith["rounds_per_sec"] / REFERENCE_ROUNDS_PER_SEC, 2)
        result["faithful_avg_test_acc"] = round(faith["avg_test_acc"], 4)
        result["faithful_total_trained_rounds"] = faith[
            "total_trained_rounds"]
        result["faithful_samples_per_sec"] = round(
            faith["samples_per_sec"], 1)
        result["faithful_spread_pct"] = round(faith["spread_pct"], 2)
        print(f"# faithful f32: {repeats}x{rounds} rounds in "
              f"{faith['measured_seconds']:.2f}s (median, spread "
              f"{faith['spread_pct']:.1f}%; acc={faith['avg_test_acc']:.4f}, "
              f"{faith['samples_per_sec']:,.0f} samples/s)", file=sys.stderr)
    seqlm = None
    if not args.skip_seqlm:
        # seqlm headline leg (promoted from scripts/bench_seqlm.py):
        # its own JSON line and its own ledger entry, judged under the
        # (seqlm_tokens_per_sec, device_kind) key — a first-seen key
        # reports NO_BASELINE until its window fills.
        seqlm = _measure_seqlm(
            steps=args.seqlm_steps or (4 if args.smoke else 30),
            seq_len=args.seqlm_seq_len or (256 if args.smoke else 2_048),
            batch=2 if args.smoke else 8,
            repeats=1 if args.smoke else min(repeats, 3),
            telemetry=tele)
        print(f"# seqlm: {seqlm['value']:,.1f} tokens/s "
              f"(seq_len={seqlm['seq_len']}, batch={seqlm['batch']}, "
              f"{seqlm['mesh_devices']} device(s), "
              f"loss={seqlm['final_loss']:.4f})", file=sys.stderr)
        print(json.dumps(seqlm))
    print(f"# fast bf16: {repeats}x{rounds} rounds in "
          f"{fast['measured_seconds']:.2f}s (median, spread "
          f"{fast['spread_pct']:.1f}%; acc={fast['avg_test_acc']:.4f}, "
          f"{fast_sps:,.0f} samples/s)", file=sys.stderr)
    print(json.dumps(result))
    if args.history_out and not args.smoke:
        # The bench trajectory as a ledger: one entry per real run, so
        # the NEXT run can be judged against the trailing trimmed
        # median (dopt.obs.regress).  Never fatal — a read-only
        # checkout still benches.
        try:
            from dopt.obs.regress import append_entry

            entry = append_entry(args.history_out, result,
                                 run_id=args.run_id)
            print(f"# appended run {entry['run_id']} "
                  f"(sha {entry['git_sha'] or 'unknown'}) to "
                  f"{args.history_out}", file=sys.stderr)
            if seqlm is not None:
                s_entry = append_entry(args.history_out, seqlm,
                                       run_id=args.run_id)
                print(f"# appended run {s_entry['run_id']} "
                      f"({s_entry['metric']}) to {args.history_out}",
                      file=sys.stderr)
        except OSError as e:
            print(f"# bench history append failed: {e}", file=sys.stderr)
    _finish_telemetry(result)


if __name__ == "__main__":
    main()
