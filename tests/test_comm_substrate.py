"""The comm substrate: per-bucket codecs inside the scatter path.

Contract (ISSUE 20 tentpole):

* The flat-bucket representation is the ONE wire every mode speaks:
  ``CommConfig`` schedules a per-bucket format (raw / bf16 / f16 / q8 /
  q4) via ``make_codec_plan``, and the lossy-link model prices the byte
  budget that maps buckets to tiers (``link_byte_budget``).
* The q8/q4 codec is stateless-stochastic — draws are a pure function
  of (round, bucket, global lane) via fold-in keys — and carries a
  per-bucket error-feedback residual in the scan like the fused buffer:
  blocked-exact, resume-exact, checkpointed as ``comm_residual``.
* Sharded (``mix_codec_gather``) and dense-reference
  (``mix_codec_reference``) paths draw BIT-IDENTICAL encodes (both
  jitted; eager-vs-jit drifts bitwise) and agree on the mixed result to
  f32 tolerance — the scatter-vs-dense parity contract extended to
  stochastic wires.
* The compositions this PR lifted from the eligibility matrix stay
  constructible: gossip scatter × comm_dtype, scatter × choco,
  federated scatter × comm_dtype, and ``CommConfig.wire_dtype`` on
  both engines.

Collective-level tests run on the 8-device virtual CPU mesh; engine
tests use tiny synthetic MLP configs (the ``test_engine`` precedent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.config import (CommConfig, DataConfig, ExperimentConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig)
from dopt.ops.compression import (lane_fold_keys, qint_decode, qint_encode,
                                  qint_wire_bytes, rand_k_compress)
from dopt.parallel.collectives import (hlo_collective_bytes,
                                       link_byte_budget, make_codec_plan,
                                       make_update_shard_spec,
                                       mix_codec_gather,
                                       mix_codec_reference,
                                       stacked_to_buckets)
from dopt.parallel.mesh import make_mesh, shard_worker_tree


def _tree(w, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": jnp.asarray(rng.normal(size=(w, 48, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(w, 8)).astype(np.float32)),
    }


def _comm_cfg(comm=None, **gk):
    gossip = dict(algorithm="dsgd", topology="circle", mode="metropolis",
                  rounds=4, local_ep=1, local_bs=32,
                  update_sharding="scatter")
    gossip.update(gk)
    return ExperimentConfig(
        name="t-comm", seed=7,
        data=DataConfig(dataset="synthetic", num_users=8, iid=True,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        gossip=GossipConfig(**gossip),
        comm=comm,
    )


def _fed_comm_cfg(comm=None, **fk):
    fed = dict(algorithm="fedavg", frac=1.0, rounds=2, local_ep=1,
               local_bs=32, update_sharding="scatter")
    fed.update(fk)
    return ExperimentConfig(
        name="t-fcomm", seed=7,
        data=DataConfig(dataset="synthetic", num_users=8, iid=True,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        federated=FederatedConfig(**fed),
        comm=comm,
    )


_CODEC = CommConfig(codec="qsgd", min_codec_bytes=256, chunk=64)


# ---------------------------------------------------------------------
# qint codec units
# ---------------------------------------------------------------------

@pytest.mark.parametrize("bits", [8, 4])
def test_qint_roundtrip_error_bound(bits):
    # Stochastic rounding is unbiased per element and the per-chunk
    # max-abs scale bounds the worst-case error at one level width.
    rng = np.random.default_rng(1)
    v = jnp.asarray(rng.normal(size=(4, 200)).astype(np.float32))
    key = jax.random.key(0)
    lane_ids = jnp.arange(4)
    payload, scale = qint_encode(v, lane_ids, key, chunk=64, bits=bits)
    out = qint_decode(payload, scale, 200, chunk=64, bits=bits)
    assert out.shape == v.shape and out.dtype == jnp.float32
    level = np.asarray(scale).repeat(64, axis=1)[:, :200]
    assert np.all(np.abs(np.asarray(out - v)) <= level + 1e-6)
    # Wire accounting matches the payload actually produced
    # (qint_wire_bytes is per lane; the slab carries 4).
    nbytes = (payload.size * payload.dtype.itemsize
              + scale.size * scale.dtype.itemsize)
    assert nbytes == 4 * qint_wire_bytes(200, chunk=64, bits=bits)


def test_qint_q4_packs_two_levels_per_byte():
    v = jnp.ones((2, 128), jnp.float32)
    payload, _ = qint_encode(v, jnp.arange(2), jax.random.key(0),
                             chunk=64, bits=4)
    assert payload.dtype == jnp.uint8 and payload.shape == (2, 64)
    p8, _ = qint_encode(v, jnp.arange(2), jax.random.key(0),
                        chunk=64, bits=8)
    assert p8.dtype == jnp.int8 and p8.shape == (2, 128)


def test_qint_zero_chunk_safe():
    # An all-zero chunk has scale 0 — decode must return exact zeros,
    # not NaN from a 0/0.
    v = jnp.zeros((2, 64), jnp.float32)
    payload, scale = qint_encode(v, jnp.arange(2), jax.random.key(3),
                                 chunk=64, bits=8)
    out = qint_decode(payload, scale, 64, chunk=64, bits=8)
    assert np.array_equal(np.asarray(out), np.zeros((2, 64), np.float32))


def test_qint_draws_are_per_global_lane():
    # The same global lane id draws the same bits regardless of which
    # slab view encodes it — the property that makes sharded and dense
    # reference encodes bit-identical.
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
    key = jax.random.key(9)
    full_p, full_s = qint_encode(v, jnp.arange(4), key, chunk=64, bits=8)
    half_p, half_s = qint_encode(v[2:], jnp.arange(2) + 2, key,
                                 chunk=64, bits=8)
    assert np.array_equal(np.asarray(full_p[2:]), np.asarray(half_p))
    assert np.array_equal(np.asarray(full_s[2:]), np.asarray(half_s))


def test_qint_rejects_bad_args():
    v = jnp.zeros((1, 8), jnp.float32)
    with pytest.raises(ValueError, match="bits"):
        qint_encode(v, jnp.arange(1), jax.random.key(0), bits=2)
    with pytest.raises(ValueError, match="even"):
        qint_encode(v, jnp.arange(1), jax.random.key(0), chunk=3, bits=4)


def test_tree_compressor_keys_fold_per_leaf():
    # rand_k/qsgd draw fold_in(key, leaf_index) — a leaf's mask depends
    # only on (key, its index), never on how many leaves ride along.
    # This is what makes blocked (scan-carried fold_in(key, t)) and
    # per-round compression streams identical.
    tree = _tree(4)
    key = jax.random.key(11)
    both = rand_k_compress(tree, 0.5, key)
    solo = rand_k_compress({"a": tree["a"]}, 0.5, key)
    assert np.array_equal(np.asarray(both["a"]), np.asarray(solo["a"]))


def test_compressor_stream_blocked_vs_per_round():
    # The round-folded key stream drawn inside a lax.scan (the blocked
    # path) is bit-identical to per-round jit dispatches of the same
    # fold — the stateless-draw contract for stochastic compressors.
    tree = {"a": jnp.asarray(np.random.default_rng(5).normal(
        size=(4, 32)).astype(np.float32))}
    key = jax.random.key(21)

    def one(t):
        return rand_k_compress(tree, 0.25, jax.random.fold_in(key, t))

    _, scanned = jax.jit(lambda: jax.lax.scan(
        lambda c, t: (c, one(t)), 0, jnp.arange(3)))()
    per_round = [jax.jit(one)(t) for t in range(3)]
    for t in range(3):
        assert np.array_equal(np.asarray(scanned["a"][t]),
                              np.asarray(per_round[t]["a"]))


# ---------------------------------------------------------------------
# Codec plan + bandwidth schedule
# ---------------------------------------------------------------------

def _spec(w=8):
    return make_update_shard_spec(_tree(w), fold=w, bucket_bytes=256)


def test_codec_plan_no_budget_compresses_large_buckets_only():
    spec = _spec()
    plan = make_codec_plan(spec, codec="qsgd", min_codec_bytes=256,
                           chunk=64)
    widths = [b - a for a, b in zip(spec.bounds, spec.bounds[1:])]
    for k, w in zip(plan.kinds, widths):
        assert k == ("q8" if w * 4 >= 256 else "raw")
    assert plan.any_codec and plan.compression > 1.0
    assert plan.dense_bytes == spec.padded * 4


def test_codec_plan_budget_escalates_largest_first():
    spec = _spec()
    loose = make_codec_plan(spec, codec="qsgd", min_codec_bytes=256,
                            chunk=64, byte_budget=spec.padded)
    tight = make_codec_plan(spec, codec="qsgd", min_codec_bytes=256,
                            chunk=64, byte_budget=1)
    # An unreachable budget degrades gracefully to q4 on every eligible
    # bucket; a loose one stops escalating once it fits.
    assert all(k in ("q4", "raw") for k in tight.kinds)
    assert "q4" in tight.kinds
    assert tight.wire_bytes <= loose.wire_bytes
    assert tight.compression > 4.0


def test_codec_plan_wire_dtype_base_and_none():
    spec = _spec()
    plain = make_codec_plan(spec)
    assert plain.kinds == ("raw",) * spec.num_buckets
    assert not plain.any_codec and plain.wire_bytes == plain.dense_bytes
    narrowed = make_codec_plan(spec, wire_dtype="bfloat16")
    assert set(narrowed.kinds) == {"bf16"}
    assert narrowed.wire_bytes == plain.wire_bytes // 2


def test_codec_plan_rejects_unknown():
    spec = _spec()
    with pytest.raises(ValueError, match="codec"):
        make_codec_plan(spec, codec="topk")
    with pytest.raises(ValueError, match="wire_dtype"):
        make_codec_plan(spec, wire_dtype="int8")


def test_link_byte_budget_goodput_factor():
    # (1 - p) / (1 + q D) of the dense payload, floored at one byte.
    assert link_byte_budget(1000) == 1000
    assert link_byte_budget(1000, msg_drop=0.5) == 500
    assert link_byte_budget(1400, msg_delay=0.2, msg_delay_max=2) == 1000
    assert link_byte_budget(10, msg_drop=0.99) >= 1


# ---------------------------------------------------------------------
# Sharded vs reference parity
# ---------------------------------------------------------------------

def test_codec_gather_matches_reference(devices):
    mesh = make_mesh(8)
    tree = shard_worker_tree(_tree(8), mesh)
    spec = make_update_shard_spec(tree, fold=8, bucket_bytes=256)
    plan = make_codec_plan(spec, codec="qsgd", min_codec_bytes=256,
                           chunk=64)
    assert plan.any_codec
    w = np.full((8, 8), 1.0 / 8, np.float32)
    buckets = stacked_to_buckets(tree, spec)
    res = [jnp.zeros_like(b) for b in buckets]
    key = jax.random.key(13)
    # BOTH paths jitted: eager-vs-jit drifts bitwise on CPU, and the
    # parity claim is about the compiled programs.
    got, gres = jax.jit(lambda b, r: mix_codec_gather(
        b, r, w, mesh, plan, key))(buckets, res)
    ref, rres = jax.jit(lambda b, r: mix_codec_reference(
        b, r, w, plan, key))(buckets, res)
    for g, f in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(f),
                                   rtol=1e-5, atol=1e-5)
    # The encodes themselves are bit-identical, so the EF residuals
    # (v - decode(encode(v)), no cross-lane reduction) match exactly.
    for g, f in zip(gres, rres):
        assert np.array_equal(np.asarray(g), np.asarray(f))


def test_codec_residual_feedback_reduces_bias(devices):
    # Two rounds of encode with the residual carried forward: the
    # second round's input v = x + e re-injects round one's
    # quantization error — classic EF, the mean of the two decodes is
    # closer to x than either alone for a coarse q4 wire.
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
    key = jax.random.key(4)
    lane_ids = jnp.arange(4)

    def enc(v, k):
        p, s = qint_encode(v, lane_ids, k, chunk=64, bits=4)
        return qint_decode(p, s, 64, chunk=64, bits=4)

    d1 = enc(x, jax.random.fold_in(key, 0))
    e1 = x - d1
    d2 = enc(x + e1, jax.random.fold_in(key, 1))
    two_round = np.asarray((d1 + d2) / 2)
    one_shot = np.asarray(d1)
    err_ef = np.abs(two_round - np.asarray(x)).mean()
    err_raw = np.abs(one_shot - np.asarray(x)).mean()
    assert err_ef < err_raw


# ---------------------------------------------------------------------
# CommConfig validation
# ---------------------------------------------------------------------

def test_comm_config_validation():
    with pytest.raises(ValueError, match="codec"):
        CommConfig(codec="topk")
    with pytest.raises(ValueError, match="wire_dtype"):
        CommConfig(wire_dtype="int8")
    with pytest.raises(ValueError, match="byte_budget_mb"):
        CommConfig(byte_budget_mb=-1.0)
    with pytest.raises(ValueError, match="min_codec_bytes"):
        CommConfig(min_codec_bytes=-5)
    with pytest.raises(ValueError, match="chunk"):
        CommConfig(chunk=7)
    with pytest.raises(ValueError, match="error_feedback"):
        CommConfig(error_feedback="maybe")


def test_comm_requires_scatter():
    from dopt.engine import GossipTrainer

    with pytest.raises(ValueError, match="scatter"):
        GossipTrainer(_comm_cfg(_CODEC, update_sharding="off"))


def test_comm_wire_dtype_conflicts_with_comm_dtype():
    from dopt.engine import FederatedTrainer, GossipTrainer

    with pytest.raises(ValueError, match="exactly one"):
        GossipTrainer(_comm_cfg(CommConfig(wire_dtype="bfloat16"),
                                comm_dtype="bfloat16"))
    with pytest.raises(ValueError, match="exactly one"):
        FederatedTrainer(_fed_comm_cfg(CommConfig(wire_dtype="float16"),
                                       comm_dtype="bfloat16"))


def test_federated_rejects_codec_but_takes_wire_dtype(devices):
    from dopt.engine import FederatedTrainer

    with pytest.raises(ValueError, match="re-binds sampled clients"):
        FederatedTrainer(_fed_comm_cfg(_CODEC))
    tr = FederatedTrainer(_fed_comm_cfg(CommConfig(wire_dtype="float16")))
    h = tr.run(rounds=2)
    assert np.isfinite(h.rows[-1]["train_loss"])


# ---------------------------------------------------------------------
# Engine integration: EF carry, blocked/resume exactness
# ---------------------------------------------------------------------

def test_codec_trainer_blocked_matches_per_round(devices):
    from dopt.engine import GossipTrainer

    a = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    assert a._codec_plan is not None and a._codec_plan.any_codec
    a.run(rounds=4)
    b = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    b.run(rounds=4, block=4)
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(a._comm_res, b._comm_res):
        assert np.array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_codec_trainer_resume_exact(devices, tmp_path):
    from dopt.engine import GossipTrainer

    cont = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    cont.run(rounds=2)
    cont.save(str(tmp_path / "ck"))
    cont.run(rounds=2)
    res = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    res.restore(str(tmp_path / "ck"))
    res.run(rounds=2)
    for x, y in zip(jax.tree.leaves(cont.params),
                    jax.tree.leaves(res.params)):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(cont._comm_res, res._comm_res):
        assert np.array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_codec_checkpoint_refusals(devices, tmp_path):
    from dopt.engine import GossipTrainer

    plain = GossipTrainer(_comm_cfg(), eval_every=1)
    plain.run(rounds=1)
    plain.save(str(tmp_path / "plain"))
    with pytest.raises(ValueError, match="comm_residual"):
        GossipTrainer(_comm_cfg(_CODEC)).restore(str(tmp_path / "plain"))
    armed = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    armed.run(rounds=1)
    armed.save(str(tmp_path / "armed"))
    with pytest.raises(ValueError, match="comm_residual"):
        GossipTrainer(_comm_cfg()).restore(str(tmp_path / "armed"))


def test_codec_scatter_vs_dense_codec_parity(devices):
    # The sharded codec trainer and a dense-reference replay of its mix
    # agree to f32 tolerance: one training round, then one codec mix of
    # the same params via the reference path.
    from dopt.engine import GossipTrainer

    tr = GossipTrainer(_comm_cfg(_CODEC), eval_every=1)
    plan, spec = tr._codec_plan, tr._scatter_spec
    buckets = stacked_to_buckets(jax.device_get(tr.params), spec)
    res = [jnp.zeros_like(b) for b in buckets]
    w = np.asarray(tr.mixing.for_round(0), np.float32)
    key = jax.random.fold_in(jax.random.key(7 ^ 0xC0DEC), 0)
    got, _ = jax.jit(lambda b, r: mix_codec_gather(
        b, r, jnp.asarray(w), tr.mesh, plan, key))(
            stacked_to_buckets(tr.params, spec),
            [jnp.zeros_like(b) for b in buckets])
    ref, _ = jax.jit(lambda b, r: mix_codec_reference(
        b, r, jnp.asarray(w), plan, key))(buckets, res)
    for g, f in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(f),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_codec_lossy_budget_still_trains(devices):
    # The full bandwidth-aware path: a byte budget priced by the
    # lossy-link preset's rates forces the q4 tier, and the resulting
    # schedule still learns on the tiny workload.
    from dopt.engine import GossipTrainer

    probe = GossipTrainer(_comm_cfg(), eval_every=1)
    spec = probe._scatter_spec
    dense = (spec.bounds[-1] - spec.bounds[0]) * 4
    budget = link_byte_budget(dense, msg_drop=0.15, msg_delay=0.2,
                              msg_delay_max=2) // 7
    del probe
    comm = CommConfig(codec="qsgd", min_codec_bytes=256, chunk=64,
                      byte_budget_mb=budget / (1 << 20))
    tr = GossipTrainer(_comm_cfg(comm), eval_every=1)
    assert "q4" in tr._codec_plan.kinds
    assert tr._codec_plan.compression > 4.0
    h = tr.run(rounds=6)
    losses = [r["avg_train_loss"] for r in h.rows]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------
# Lifted eligibility rows stay constructible
# ---------------------------------------------------------------------

def test_lifted_rows_constructible(devices):
    from dopt.engine import FederatedTrainer, GossipTrainer

    # gossip scatter × comm_dtype — the deleted wire-dtype rejection.
    g1 = GossipTrainer(_comm_cfg(comm_dtype="bfloat16"), eval_every=1)
    g1.run(rounds=1)
    # gossip scatter × choco — quantized gossip over the bucket wire.
    g2 = GossipTrainer(_comm_cfg(algorithm="choco", compression="qsgd",
                                 choco_gamma=0.3),
                       eval_every=1)
    g2.run(rounds=2)
    # gossip scatter × CommConfig.wire_dtype narrowing.
    g3 = GossipTrainer(_comm_cfg(CommConfig(wire_dtype="bfloat16")),
                       eval_every=1)
    g3.run(rounds=1)
    # federated scatter × comm_dtype — the deleted federated rejection.
    f1 = FederatedTrainer(_fed_comm_cfg(comm_dtype="bfloat16"))
    h = f1.run(rounds=2)
    assert np.isfinite(h.rows[-1]["train_loss"])


def test_codec_composition_refusals(devices):
    from dopt.engine import GossipTrainer

    with pytest.raises(ValueError, match="choco already quantizes"):
        GossipTrainer(_comm_cfg(_CODEC, algorithm="choco",
                                compression="qsgd"))
    with pytest.raises(ValueError, match="gathered-bucket wire"):
        GossipTrainer(_comm_cfg(_CODEC, comm_impl="shift"))


# ---------------------------------------------------------------------
# HLO byte attribution
# ---------------------------------------------------------------------

def test_hlo_bytes_by_dtype_and_op():
    hlo = "\n".join([
        "  ag = f32[8,128]{1,0} all-gather(f32[1,128] %x), dims={0}",
        "  ag2 = u8[8,64]{1,0} all-gather-start(u8[1,64] %p), dims={0}",
        "  rs = bf16[4,32]{1,0} reduce-scatter(bf16[8,32] %y), dims={0}",
        "  add = f32[8,128]{1,0} add(f32[8,128] %a, f32[8,128] %b)",
    ])
    out = hlo_collective_bytes(hlo)
    assert out["all-gather"] == 8 * 128 * 4 + 8 * 64
    assert out["reduce-scatter"] == 4 * 32 * 2
    assert out["total"] == out["all-gather"] + out["reduce-scatter"]
    assert out["by_dtype"] == {"f32": 8 * 128 * 4, "u8": 8 * 64,
                               "bf16": 4 * 32 * 2}
    assert out["by_op_dtype"]["all-gather"] == {"f32": 8 * 128 * 4,
                                                "u8": 8 * 64}
    assert out["by_op_dtype"]["reduce-scatter"] == {"bf16": 4 * 32 * 2}


def test_codec_round_program_ships_packed_bytes(devices):
    # The compiled codec round really moves packed payload + f32
    # sidecar instead of the dense f32 slabs — the bytes-on-wire claim
    # measured from the program, not the docstring.  (Totals are NOT
    # compared across the two programs here: the raw leg's
    # reduce-scatter results are per-shard buffers while the codec's
    # all-gather materialises fleet slabs — the op-kind accounting
    # unfairness dopt.analysis.comm_bytes documents; the dtype
    # attribution is the like-for-like claim.)
    from dopt.engine import GossipTrainer

    raw = GossipTrainer(_comm_cfg(), eval_every=1 << 20)
    _, lo_raw = raw.lower_round()
    raw_bytes = hlo_collective_bytes(lo_raw.compile().as_text())
    codec = GossipTrainer(_comm_cfg(_CODEC), eval_every=1 << 20)
    _, lo_c = codec.lower_round()
    c_bytes = hlo_collective_bytes(lo_c.compile().as_text())
    packed = (c_bytes["by_dtype"].get("u8", 0)
              + c_bytes["by_dtype"].get("s8", 0))
    assert packed > 0, c_bytes
    # f32 is demoted from payload to sidecar: the codec program's f32
    # collective bytes are a small fraction of the raw program's.
    assert (c_bytes["by_dtype"].get("f32", 0)
            < 0.25 * raw_bytes["by_dtype"]["f32"]), (c_bytes, raw_bytes)
    # And the packed payload dominates the codec program's own wire.
    assert packed > 0.5 * c_bytes["total"], c_bytes
