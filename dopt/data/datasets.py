"""Dataset loading without torchvision (zero-egress environment).

The reference downloads MNIST/FMNIST/CIFAR-10/CIFAR-100 through
torchvision (``Decentralized Optimization/src/utils.py:97-144``,
``Distributed Optimization/src/utils.py:72-106``) and applies
ToTensor + Normalize.  This module reads the same raw artifact formats
directly — IDX (MNIST/FMNIST), CIFAR python pickles, LIBSVM text (a9a)
— from a local directory, and falls back to a deterministic *learnable*
synthetic dataset when no raw files exist, so every pipeline stage is
exercisable offline.

All arrays are NHWC float32 (TPU-native layout; the reference's NCHW is
a torch convention, not a capability).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from dataclasses import dataclass
from pathlib import Path

import numpy as np

# Reference normalisation constants (P1 utils.py:100-110).
_NORM = {
    "mnist": ((0.1307,), (0.3081,)),
    "fmnist": ((0.5,), (0.5,)),
    "cifar10": ((0.5, 0.5, 0.5), (0.5, 0.5, 0.5)),
    "cifar100": ((0.5, 0.5, 0.5), (0.5, 0.5, 0.5)),
}


@dataclass(frozen=True)
class Dataset:
    """A fully-materialised split pair: features are NHWC float32 (or
    [N, D] for tabular), labels int32."""

    name: str
    train_x: np.ndarray
    train_y: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray

    @property
    def num_classes(self) -> int:
        return int(max(self.train_y.max(), self.test_y.max())) + 1

    @property
    def input_shape(self) -> tuple[int, ...]:
        return tuple(self.train_x.shape[1:])


# --------------------------------------------------------------------
# Raw-format parsers
# --------------------------------------------------------------------

def _read_idx(path: Path) -> np.ndarray:
    """Parse an IDX file (the raw MNIST/FMNIST format), gzipped or not."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class _Finder:
    """File discovery under a data root: one recursive walk per
    ``load_dataset`` call (cached for that call only, so files added
    between calls are seen), with dataset-name-aware ranking — under a
    shared root holding both ``MNIST/raw/`` and ``FashionMNIST/raw/``
    (identical IDX filenames, torchvision layout) the path whose parents
    mention the requested dataset wins."""

    def __init__(self, data_dir: Path, prefer: tuple[str, ...] = (),
                 avoid=()):
        """``avoid`` is a tuple of substrings or a predicate on the
        lower-cased path string; avoided-only hits count as missing."""
        self.data_dir = data_dir
        self.prefer = tuple(t.lower() for t in prefer)
        if callable(avoid):
            self._avoided = avoid
        else:
            toks = tuple(t.lower() for t in avoid)
            self._avoided = (lambda s: any(t in s for t in toks)) if toks else (lambda s: False)
        self._table: dict[str, list[Path]] | None = None

    def _listing(self) -> dict[str, list[Path]]:
        if self._table is None:
            table: dict[str, list[Path]] = {}
            for p in sorted(self.data_dir.rglob("*")):
                if p.is_file():
                    table.setdefault(p.name, []).append(p)
            self._table = table
        return self._table

    def _rank(self, p: Path) -> tuple[int, int]:
        s = str(p).lower()
        preferred = any(t in s for t in self.prefer)
        return (0 if preferred else 1, 1 if self._avoided(s) else 0)

    def find(self, names: list[str]) -> Path | None:
        for name in names:
            for cand in (self.data_dir / name, self.data_dir / (name + ".gz")):
                if cand.is_file():
                    return cand
            table = self._listing()
            hits = table.get(name, []) + table.get(name + ".gz", [])
            if hits:
                if all(self._avoided(str(h).lower()) for h in hits):
                    # every hit sits under an avoided name -> the wrong
                    # dataset's files; treat as missing
                    continue
                return min(hits, key=self._rank)
        return None


def _find(data_dir: Path, names: list[str]) -> Path | None:
    return _Finder(data_dir).find(names)


def _load_mnist_like(name: str, data_dir: Path) -> Dataset | None:
    files = {
        "train_x": ["train-images-idx3-ubyte", "train-images.idx3-ubyte"],
        "train_y": ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"],
        "test_x": ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"],
        "test_y": ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"],
    }
    if name == "mnist":
        finder = _Finder(data_dir, prefer=("mnist",), avoid=("fashion", "fmnist"))
    else:
        # "mnist" is a substring of "fashionmnist", so express the avoid
        # rule as a predicate: a path that mentions mnist but not fashion.
        finder = _Finder(
            data_dir, prefer=("fashion", "fmnist"),
            avoid=lambda s: "mnist" in s and "fashion" not in s and "fmnist" not in s,
        )
    paths = {k: finder.find(v) for k, v in files.items()}
    if any(p is None for p in paths.values()):
        return None
    mean, std = _NORM[name]
    xs = {}
    for split in ("train", "test"):
        x = _read_idx(paths[f"{split}_x"]).astype(np.float32) / 255.0
        x = (x - mean[0]) / std[0]
        xs[split] = x[..., None]  # NHWC
    return Dataset(
        name=name,
        train_x=xs["train"],
        train_y=_read_idx(paths["train_y"]).astype(np.int32),
        test_x=xs["test"],
        test_y=_read_idx(paths["test_y"]).astype(np.int32),
    )


def _load_cifar(name: str, data_dir: Path) -> Dataset | None:
    if name == "cifar10":
        batch_names = [f"data_batch_{i}" for i in range(1, 6)]
        test_names = ["test_batch"]
        label_key = b"labels"
    else:
        batch_names = ["train"]
        test_names = ["test"]
        label_key = b"fine_labels"

    finder = _Finder(data_dir, prefer=("cifar-100" if name == "cifar100" else "cifar-10",),
                     avoid=("cifar-100",) if name == "cifar10" else ())

    def read(names):
        xs, ys = [], []
        for n in names:
            p = finder.find([n])
            if p is None:
                return None, None
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[label_key])
        return np.concatenate(xs), np.asarray(ys, dtype=np.int32)

    train_x, train_y = read(batch_names)
    test_x, test_y = read(test_names)
    if train_x is None or test_x is None:
        return None
    mean, std = _NORM[name]
    mean_a = np.asarray(mean, np.float32)
    std_a = np.asarray(std, np.float32)

    def to_nhwc(x):
        x = x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        return (x - mean_a) / std_a

    return Dataset(name, to_nhwc(train_x), train_y, to_nhwc(test_x), test_y)


def _load_a9a(data_dir: Path) -> Dataset | None:
    """LIBSVM a9a: 123 binary features, labels ±1 → {0,1} (the ADMM
    logistic-regression benchmark config, BASELINE.json config 4)."""
    train_p = _find(data_dir, ["a9a", "a9a.txt", "a9a.train"])
    test_p = _find(data_dir, ["a9a.t", "a9a.test"])
    if train_p is None:
        return None

    def parse(path: Path, d: int = 123):
        xs, ys = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                ys.append(1 if float(parts[0]) > 0 else 0)
                row = np.zeros(d, np.float32)
                for tok in parts[1:]:
                    idx, val = tok.split(":")
                    row[int(idx) - 1] = float(val)
                xs.append(row)
        return np.stack(xs), np.asarray(ys, np.int32)

    train_x, train_y = parse(train_p)
    if test_p is not None:
        test_x, test_y = parse(test_p)
    else:
        # Shuffle before the 80/20 cut: LIBSVM dumps are often
        # label-sorted, and an ordered cut would skew the test split.
        n = len(train_x)
        perm = np.random.default_rng(0).permutation(n)
        train_x, train_y = train_x[perm], train_y[perm]
        cut = int(0.8 * n)
        train_x, test_x = train_x[:cut], train_x[cut:]
        train_y, test_y = train_y[:cut], train_y[cut:]
    return Dataset("a9a", train_x, train_y, test_x, test_y)


# --------------------------------------------------------------------
# Synthetic fallback
# --------------------------------------------------------------------

def make_synthetic(
    *,
    input_shape: tuple[int, ...] = (28, 28, 1),
    num_classes: int = 10,
    train_size: int = 2048,
    test_size: int = 512,
    seed: int = 0,
    noise: float = 0.7,
    name: str = "synthetic",
) -> Dataset:
    """Deterministic learnable classification data.

    Each class gets a random smooth prototype in feature space; samples
    are prototype + Gaussian noise.  Linearly separable enough that both
    an MLP and the reference CNNs reach high accuracy in a few epochs,
    so training-curve smoke tests are meaningful without real data.
    """
    rng = np.random.default_rng(seed)
    dim = int(np.prod(input_shape))
    protos = rng.normal(0.0, 1.0, size=(num_classes, dim)).astype(np.float32)

    def split(n, salt):
        r = np.random.default_rng(seed * 7919 + salt)
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        x = protos[y] + r.normal(0.0, noise, size=(n, dim)).astype(np.float32)
        return x.reshape((n, *input_shape)).astype(np.float32), y

    train_x, train_y = split(train_size, 1)
    test_x, test_y = split(test_size, 2)
    return Dataset(name, train_x, train_y, test_x, test_y)


# --------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------

def load_dataset(
    dataset: str,
    *,
    data_dir: str | os.PathLike | None = None,
    synthetic_fallback: bool = True,
    train_size: int = 2048,
    test_size: int = 512,
    seed: int = 0,
    input_shape: tuple[int, ...] | None = None,
    num_classes: int | None = None,
) -> Dataset:
    """Load a dataset by name (reference ``get_dataset`` equivalent).

    Looks for raw files under ``data_dir`` (or ``$DOPT_DATA_DIR``); if
    absent and ``synthetic_fallback``, returns a shape-compatible
    synthetic dataset so the full pipeline still runs offline.
    """
    name = dataset.lower()
    if name in ("cifar",):
        name = "cifar10"
    roots = []
    if data_dir is not None:
        roots.append(Path(data_dir))
    if os.environ.get("DOPT_DATA_DIR"):
        roots.append(Path(os.environ["DOPT_DATA_DIR"]))

    shapes = {
        "mnist": ((28, 28, 1), 10),
        "fmnist": ((28, 28, 1), 10),
        "cifar10": ((32, 32, 3), 10),
        "cifar100": ((32, 32, 3), 100),
        "a9a": ((123,), 2),
    }

    for root in roots:
        if not root.exists():
            continue
        ds = None
        if name in ("mnist", "fmnist"):
            ds = _load_mnist_like(name, root)
        elif name in ("cifar10", "cifar100"):
            ds = _load_cifar(name, root)
        elif name == "a9a":
            ds = _load_a9a(root)
        if ds is not None:
            return ds

    if name == "synthetic" or (synthetic_fallback and name in shapes):
        if name == "synthetic":
            shape = input_shape or (28, 28, 1)
            ncls = num_classes or 10
        else:
            shape, ncls = shapes[name]
        return make_synthetic(
            input_shape=shape, num_classes=ncls, train_size=train_size,
            test_size=test_size, seed=seed, name=f"synthetic[{name}]",
        )
    raise FileNotFoundError(
        f"no raw files for {dataset!r} under {roots or '$DOPT_DATA_DIR'} "
        "and synthetic_fallback is off"
    )
