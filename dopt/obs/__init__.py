"""dopt.obs — unified telemetry: event stream, span tracing, sinks.

The signals the ROADMAP's service mode needs (fault ledger, phase
fractions, live run metrics) used to be scattered across
``History.rows``, the ledger, bench-only JSON lines and one-off
scripts.  This package is the one substrate:

* a structured **JSONL event stream** with a versioned schema
  (``dopt.obs.events``): per-round ``round`` events, host-mirror
  ``gauge`` events, the fault ledger re-emitted as typed ``fault``
  events, plus ``phase``/``bench``/``warning`` producer events;
* host-side **span tracing** (``dopt.obs.spans``) with a Chrome-trace
  export, hooked into the engines' existing ``PhaseTimers`` sites;
* a **sink layer** (``dopt.obs.sinks``): JSONL file, in-memory ring,
  Prometheus text snapshot;
* a **streaming health monitor** (``dopt.obs.monitor`` +
  ``dopt.obs.rules``): a declarative rule set evaluated over the live
  stream (in-process sink or JSONL tail), emitting ``alert`` events
  and an end-of-run ``HealthReport`` verdict — with a scrape endpoint
  (``python -m dopt.obs.serve``: /metrics + /healthz), a live terminal
  tail (``python -m dopt.obs.watch``), and a bench perf-regression
  ledger (``dopt.obs.regress`` over ``results/bench_history.jsonl``).

Hard invariants:

* **Off path** — ``trainer.telemetry`` defaults to None and every
  emission site is python-gated on it, entirely on the HOST side of
  the post-fetch boundary: with telemetry off the engines run the
  exact pre-change host loop and compile the exact pre-change device
  programs (pinned by tests/test_obs.py's bit-identity test).
* **Execution-path equality** — events of the deterministic kinds
  (``round``/``fault``/``gauge``) are derived only from the same
  host-replay data the ledger already uses, at the same post-fetch
  points, so per-round and blocked execution emit bit-identical
  streams (``canonical()`` is the comparison form).
* **Resume watermark** — ``Telemetry.to_jsonl(path, resume=True)``
  recovers the highest streamed round from the file and suppresses
  re-emission below it, so a killed-and-resumed run continues the
  stream with a gapless, duplicate-free round sequence
  (``python -m dopt.obs.check`` enforces it).

Emission cadence note: the per-round ``round``/``fault``/``gauge``
bundle replays identically on every path; ``consensus_distance`` is
computed from the final device state once per ``run()`` call (one
fetch, identical across paths for an identical call pattern), and
``phase`` events come from profiler-traced windows (bench.py).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

from dopt.obs.events import (DETERMINISTIC_KINDS, KINDS, SCHEMA_VERSION,
                             canonical, check_stream, make_event,
                             sanitize_metrics, validate_event)
from dopt.obs.latency import (SLO_LATENCIES, LatencyHistogram,
                              summarize_latency_events)
from dopt.obs.monitor import HealthMonitor, HealthReport, JsonlTail
from dopt.obs.rules import RULES, build_rules, default_rules
from dopt.obs.sinks import JsonlSink, MemorySink, PrometheusSink, Sink
from dopt.obs.spans import SpanTracer

__all__ = [
    "DETERMINISTIC_KINDS", "KINDS", "RULES", "SCHEMA_VERSION",
    "SLO_LATENCIES", "FleetAggregator", "FleetMetricsServer",
    "HealthMonitor", "HealthReport", "JsonlSink", "JsonlTail",
    "LatencyHistogram", "MemorySink", "PrometheusSink", "Sink",
    "SpanTracer", "Telemetry", "attach", "build_rules", "canonical",
    "check_stream", "consensus_distance", "default_rules",
    "first_divergence", "make_event", "sanitize_metrics",
    "summarize_latency_events", "validate_event",
]


def __getattr__(name: str):
    # The fleet aggregation layer and the stream differ are imported
    # lazily: they are CLI-facing modules with their own http.server /
    # argparse surface, and the hot telemetry path (engines importing
    # dopt.obs per round bundle) should not pay for them.
    if name in ("FleetAggregator", "FleetMetricsServer"):
        from dopt.obs import aggregate

        return getattr(aggregate, name)
    if name == "first_divergence":
        from dopt.obs.diff import first_divergence

        return first_divergence
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class Telemetry:
    """Emitter facade: builds schema-stamped events, fans them out to
    the sinks, owns the span tracer and the monotonic round watermark."""

    def __init__(self, sinks: Iterable[Sink] = (), *, watermark: int = 0):
        self.sinks: list[Sink] = list(sinks)
        self.tracer = SpanTracer()
        self.watermark = int(watermark)

    @classmethod
    def to_jsonl(cls, path, *, resume: bool = False,
                 ring: int = 0) -> "Telemetry":
        """JSONL-file telemetry.  ``resume=True`` appends and recovers
        the round watermark from the existing file (kill-and-resume
        continues the stream instead of duplicating rounds); ``ring``
        > 0 additionally keeps the last N events in memory
        (``.sinks[-1].events``)."""
        wm = 0
        if resume:
            prev = JsonlSink.scan_watermark(path)
            wm = 0 if prev is None else prev + 1
        sinks: list[Sink] = [JsonlSink(path, append=resume)]
        if ring:
            sinks.append(MemorySink(capacity=ring))
        return cls(sinks, watermark=wm)

    # -- emission ------------------------------------------------------
    def emit(self, kind: str, **fields: Any) -> dict[str, Any]:
        ev = make_event(kind, **fields)
        for s in self.sinks:
            s.emit(ev)
        return ev

    def emit_round_bundle(self, t: int, *, engine: str,
                          metrics: Mapping[str, Any],
                          faults: Iterable[Mapping[str, Any]] = (),
                          gauges: Mapping[str, float] | None = None) -> bool:
        """One round's deterministic events, in the canonical order:
        the fault-ledger rows (typed), the host-mirror gauges, then the
        ``round`` event LAST — it is the bundle's commit record: a
        kill-torn bundle has no round event, so ``repair_tail`` drops
        the orphans and the resumed run re-emits the bundle whole
        (round-first would seal a bundle whose gauges never landed).
        Suppressed wholesale (returns False) below the resume
        watermark; advances the watermark past ``t``."""
        t = int(t)
        if t < self.watermark:
            return False
        bundle = [make_event("fault", round=int(r["round"]),
                             worker=int(r["worker"]), fault=str(r["kind"]),
                             action=str(r["action"])) for r in faults]
        bundle.extend(make_event("gauge", round=t, name=name,
                                 value=float(value), engine=engine)
                      for name, value in (gauges or {}).items())
        bundle.append(make_event("round", round=t, engine=engine,
                                 metrics=sanitize_metrics(metrics)))
        # One batched dispatch per round: the JSONL sink turns the
        # bundle into a single flushed write, so a kill never tears a
        # round's fault events apart from its round event (the resume
        # watermark would re-emit them as duplicates otherwise).
        for s in self.sinks:
            s.emit_many(bundle)
        self.watermark = t + 1
        return True

    # -- spans ---------------------------------------------------------
    def span(self, name: str):
        return self.tracer.span(name)

    def write_trace(self, path):
        return self.tracer.write_chrome(path)

    def close(self) -> None:
        for s in self.sinks:
            s.close()


def attach(trainer, telemetry: Telemetry, *, fresh: bool = False,
           checkpoint_every: int | None = None) -> Telemetry:
    """Wire a Telemetry into a trainer: sets ``trainer.telemetry``
    (read by the engines' python-gated emission sites), hooks the span
    tracer into the trainer's ``PhaseTimers`` (every existing
    ``phase``/``measure`` site becomes a span), and emits the stream
    segment header.  ``fresh=True`` resets the round watermark to 0 —
    for a NEW logical run sharing a sink with earlier ones (bench's
    legs); resumed runs keep the watermark ``to_jsonl(resume=True)``
    recovered.  ``checkpoint_every`` stamps the run's configured
    checkpoint cadence (rounds) on the header so the monitor's
    checkpoint_cadence rule knows what to expect without being told
    out of band."""
    if fresh:
        telemetry.watermark = 0
    trainer.telemetry = telemetry
    trainer.timers.tracer = telemetry.tracer
    engine = getattr(trainer, "engine_kind", type(trainer).__name__.lower())
    # The segment starts wherever the trainer will actually emit from:
    # a checkpoint-resumed trainer streaming into a FRESH file starts
    # at trainer.round, not 0 — a header claiming 0 would make the
    # checker reject the (valid) stream at the first round event.
    start = max(telemetry.watermark, int(getattr(trainer, "round", 0) or 0))
    telemetry.watermark = start
    telemetry.emit("run", engine=engine,
                   name=getattr(getattr(trainer, "cfg", None), "name", None)
                   or "run",
                   round=start,
                   workers=getattr(trainer, "num_workers", None),
                   checkpoint_every=(int(checkpoint_every)
                                     if checkpoint_every else None))
    return telemetry


def consensus_distance(stacked, center=None) -> float:
    """Mean over workers of ‖xᵢ − c‖₂ for a worker-stacked pytree —
    the fleet-disagreement meter.  ``center`` defaults to the stacked
    mean (gossip); the federated engines pass theta.  One device
    reduction + one scalar fetch; deterministic for bit-identical
    inputs, so every execution path of the same run reports the same
    value."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(stacked)
    centers = (jax.tree.leaves(center) if center is not None
               else [leaf.astype(jnp.float32).mean(axis=0)
                     for leaf in leaves])
    sq = None
    for p, c in zip(leaves, centers):
        d = (p.astype(jnp.float32)
             - c.astype(jnp.float32)[None]).reshape(p.shape[0], -1)
        s = (d * d).sum(axis=1)
        sq = s if sq is None else sq + s
    return float(jnp.sqrt(sq).mean())
