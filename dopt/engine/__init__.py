from dopt.engine.federated import FederatedTrainer
from dopt.engine.gossip import GossipTrainer
from dopt.engine.seqlm import SeqLMTrainer

__all__ = ["FederatedTrainer", "GossipTrainer", "SeqLMTrainer"]
