"""The local-training step: per-worker SGD epochs as a ``lax.scan``.

This is the reference's inner hot loop (``Client.update_weights``,
``Decentralized Optimization/src/clients.py:36-53`` /
``Client.local_update``, ``Distributed Optimization/src/clients.py:34-59``)
turned into a pure function: given a worker's params + momentum and a
[S, B, ...] batch stack (S = local_ep × steps_per_epoch from the batch
plan), scan SGD steps and return the new state plus per-step metrics.

``make_local_update`` builds the per-worker function; ``vmap`` over the
leading worker axis turns it into the stacked-engine step.  FedProx and
FedADMM enter as gradient edits (``dopt.optim``), with the global model
``theta`` broadcast (in_axes=None) and the ADMM duals stacked per
worker — the dual variables are worker-sharded pytrees, exactly the
TPU mapping SURVEY §2.3 calls for.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from dopt.models.losses import accuracy, cross_entropy, l2_regulariser
from dopt.optim import (SGDState, admm_grad_edit, prox_grad_edit,
                        scaffold_grad_edit, sgd_step)


def validate_optimizer(cfg) -> None:
    """Only 'sgd' exists (the reference's single optimizer,
    clients.py:14); anything else fails loudly at trainer construction
    rather than silently running SGD."""
    if cfg.optim.optimizer.lower() != "sgd":
        raise ValueError(
            f"unknown optimizer {cfg.optim.optimizer!r}: only 'sgd' "
            "exists (the reference's single optimizer, clients.py:14)")


def prepare_holdout(cfg, index_matrix, mesh, *, batch_size):
    """Shared trainer setup for the reference's local train/val holdout
    (``train_val_test`` — P1 clients.py:16-34 / P2 clients.py:19-32).

    Returns ``(use_holdout, train_matrix, (vidx_dev, vw_dev))``: the
    training index matrix (the full shard when the holdout is off) and
    per-worker local-val eval stacks placed with the worker axis sharded.
    When off, the val stacks are [W, 1, 1] zero dummies so jitted round
    signatures stay static either way — both engines rely on that
    contract."""
    import numpy as np

    from dopt.data import holdout_split, stacked_eval_batches
    from dopt.parallel.mesh import worker_sharding

    w = index_matrix.shape[0]
    use = cfg.data.local_holdout > 0.0
    if use:
        train_matrix, val_matrix = holdout_split(
            index_matrix, fraction=cfg.data.local_holdout,
            mode=cfg.data.holdout_mode, seed=cfg.seed)
        vi, vw = stacked_eval_batches(val_matrix, batch_size=batch_size)
    else:
        train_matrix = index_matrix
        vi = np.zeros((w, 1, 1), np.int32)
        vw = np.zeros((w, 1, 1), np.float32)
    sh = worker_sharding(mesh)
    return use, train_matrix, (jax.device_put(vi, sh), jax.device_put(vw, sh))


def _apply_update(p, m, g, *, lr, momentum, update_impl):
    """Dispatch the momentum-SGD update: 'jnp' (tree.map two-liner) or
    'pallas' (fused single-pass kernel, dopt.ops.fused_update)."""
    if update_impl == "pallas":
        from dopt.ops import fused_sgd_momentum_tree

        return fused_sgd_momentum_tree(p, m, g, lr=lr, mu=momentum)
    p, st = sgd_step(p, SGDState(m), g, lr=lr, momentum=momentum)
    return p, st.momentum


def _make_step_core(apply_fn, *, lr, momentum, algorithm, rho, l2,
                    update_impl):
    """One SGD step on concrete batch arrays — the shared body of both
    local-update variants (materialised batches and on-device gather)."""

    def step_core(p, m, x, y, w, theta=None, alpha=None):
        def loss_fn(p_):
            out = apply_fn({"params": p_}, x)
            loss = cross_entropy(out, y, w)
            if l2:
                loss = loss + l2_regulariser(p_, l2)
            return loss, out

        (loss, out), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        if algorithm == "fedprox":
            g = prox_grad_edit(g, p, theta, rho)
        elif algorithm == "fedadmm":
            g = admm_grad_edit(g, p, theta, alpha, rho)
        elif algorithm == "scaffold":
            # theta slot carries the server control variate c (broadcast),
            # alpha slot the client control variate c_i (worker-stacked).
            g = scaffold_grad_edit(g, theta, alpha)
        p, m = _apply_update(p, m, g, lr=lr, momentum=momentum,
                             update_impl=update_impl)
        return p, m, loss, accuracy(out, y, w)

    return step_core


def make_local_update(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
):
    """Build the per-worker local-update function.

    algorithm: 'sgd' (FedAvg / D-SGD local step), 'fedprox', 'fedadmm',
    'scaffold' (theta slot = server control c, alpha slot = client c_i).
    Returns fn(params, mom, bx, by, bw, theta=None, alpha=None) ->
    (new_params, new_mom, losses[S], accs[S]).
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl)

    def local_update(params, mom, bx, by, bw, theta=None, alpha=None):
        def step(carry, batch):
            p, m = carry
            x, y, w = batch
            p, m, loss, acc = core(p, m, x, y, w, theta, alpha)
            return (p, m), (loss, acc)

        (params, mom), (losses, accs) = jax.lax.scan(step, (params, mom), (bx, by, bw))
        return params, mom, losses, accs

    return local_update


def make_stacked_local_update(apply_fn, *, lr, momentum, algorithm="sgd",
                              rho=0.0, l2=0.0, update_impl="jnp"):
    """vmap the per-worker update over the leading worker axis.

    theta (global model) is broadcast; alpha (ADMM duals) is stacked.
    """
    fn = make_local_update(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl)
    if algorithm == "sgd":
        return jax.vmap(lambda p, m, bx, by, bw: fn(p, m, bx, by, bw))
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, bx, by, bw, theta: fn(p, m, bx, by, bw, theta=theta),
            in_axes=(0, 0, 0, 0, 0, None),
        )
    return jax.vmap(
        lambda p, m, bx, by, bw, theta, alpha: fn(p, m, bx, by, bw,
                                                  theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, 0, None, 0),
    )


def make_local_update_gather(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
):
    """Like ``make_local_update`` but gathers each minibatch from the full
    on-device dataset inside the step scan: the caller passes the [S, B]
    index/weight plan plus the resident train arrays instead of
    materialised [S, B, ...] batches.  Peak activation memory drops from
    O(S·B·|x|) to O(B·|x|), which is what lets the fused multi-round
    block path keep K rounds of plans on device at once.

    Returns fn(params, mom, idx, bw, train_x, train_y, theta=None,
    alpha=None) -> (new_params, new_mom, losses[S], accs[S]).
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl)

    def local_update(params, mom, idx, bw, train_x, train_y,
                     theta=None, alpha=None):
        def step(carry, batch):
            p, m = carry
            i, w = batch
            p, m, loss, acc = core(p, m, train_x[i], train_y[i], w, theta, alpha)
            return (p, m), (loss, acc)

        (params, mom), (losses, accs) = jax.lax.scan(step, (params, mom), (idx, bw))
        return params, mom, losses, accs

    return local_update


def make_stacked_local_update_gather(apply_fn, *, lr, momentum,
                                     algorithm="sgd", rho=0.0, l2=0.0,
                                     update_impl="jnp"):
    """vmap the gather-variant over the leading worker axis; train arrays
    and theta broadcast, ADMM duals stacked per worker."""
    fn = make_local_update_gather(apply_fn, lr=lr, momentum=momentum,
                                  algorithm=algorithm, rho=rho, l2=l2,
                                  update_impl=update_impl)
    if algorithm == "sgd":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty: fn(p, m, idx, bw, tx, ty),
            in_axes=(0, 0, 0, 0, None, None),
        )
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, theta: fn(p, m, idx, bw, tx, ty,
                                                    theta=theta),
            in_axes=(0, 0, 0, 0, None, None, None),
        )
    return jax.vmap(
        lambda p, m, idx, bw, tx, ty, theta, alpha: fn(
            p, m, idx, bw, tx, ty, theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, None, None, None, 0),
    )


def make_local_update_epochs(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
):
    """Local update with the reference's EPOCH structure: an outer scan
    over local epochs, each running its steps then evaluating the
    worker's local validation holdout — ``Client.update_weights``'s
    per-epoch ``inference`` + history row
    (``Decentralized Optimization/src/clients.py:38-50`` /
    ``Distributed Optimization/src/clients.py:37-57``).

    Returns fn(params, mom, idx, bw, train_x, train_y, vidx, vw,
    theta=None, alpha=None) -> (new_params, new_mom, em) where ``idx``/
    ``bw`` are [E, S', B] epoch-major plans, ``vidx``/``vw`` the [Sv, Bv]
    local-val eval stacks, and ``em`` maps per-epoch [E] arrays:

    * train_loss — mean over the epoch's batches of the batch-mean loss
      (``sum(train_loss)/len(train_loss)``, clients.py:47)
    * train_acc  — epoch correct count / train-set size
      (``train_acc += corr/total``, clients.py:44-45)
    * val_acc / val_loss_sum / val_loss_mean — post-epoch local-val
      metrics in both reference flavours (P1 ``inference`` sums batch
      losses, P2 averages them).
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl)
    ev = make_evaluator(apply_fn)

    def local_update(params, mom, idx, bw, train_x, train_y, vidx, vw,
                     theta=None, alpha=None):
        vx = train_x[vidx]
        vy = train_y[vidx]

        def epoch(carry, ep):
            p, m = carry
            ei, ew = ep

            def step(c, b):
                p_, m_ = c
                i, w_ = b
                p_, m_, loss, acc = core(p_, m_, train_x[i], train_y[i], w_,
                                         theta, alpha)
                return (p_, m_), (loss, acc * w_.sum(), w_.sum())

            (p, m), (losses, corrects, counts) = jax.lax.scan(
                step, (p, m), (ei, ew))
            vm = ev(p, vx, vy, vw)
            em = {
                "train_loss": losses.mean(),
                "train_acc": corrects.sum() / jnp.maximum(counts.sum(), 1.0),
                "val_acc": vm["acc"],
                "val_loss_sum": vm["loss_sum"],
                "val_loss_mean": vm["loss_mean"],
            }
            return (p, m), em

        (params, mom), em = jax.lax.scan(epoch, (params, mom), (idx, bw))
        return params, mom, em

    return local_update


def make_stacked_local_update_epochs(apply_fn, *, lr, momentum,
                                     algorithm="sgd", rho=0.0, l2=0.0,
                                     update_impl="jnp"):
    """vmap the epoch-structured update over the leading worker axis;
    train arrays and theta broadcast, per-worker plans / val stacks /
    ADMM duals stacked."""
    fn = make_local_update_epochs(apply_fn, lr=lr, momentum=momentum,
                                  algorithm=algorithm, rho=rho, l2=l2,
                                  update_impl=update_impl)
    if algorithm == "sgd":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, vi, vw_: fn(p, m, idx, bw, tx, ty,
                                                      vi, vw_),
            in_axes=(0, 0, 0, 0, None, None, 0, 0),
        )
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, vi, vw_, theta: fn(
                p, m, idx, bw, tx, ty, vi, vw_, theta=theta),
            in_axes=(0, 0, 0, 0, None, None, 0, 0, None),
        )
    return jax.vmap(
        lambda p, m, idx, bw, tx, ty, vi, vw_, theta, alpha: fn(
            p, m, idx, bw, tx, ty, vi, vw_, theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, None, None, 0, 0, None, 0),
    )


def make_evaluator(apply_fn):
    """Batched evaluation over a static [S, B, ...] eval stack.

    Returns fn(params, ex, ey, ew) -> dict with weighted sums so the
    caller can form either reference metric flavour:
    P1 ``inference`` returns (acc, summed-per-batch loss)
    (``Decentralized Optimization/src/clients.py:61-75``), P2 returns
    (acc, mean-per-batch loss) (``Distributed Optimization/src/clients.py:71-86``).
    """

    def evaluate(params, ex, ey, ew):
        def step(carry, batch):
            x, y, w = batch
            out = apply_fn({"params": params}, x)
            loss = cross_entropy(out, y, w)          # weighted mean over batch
            correct = accuracy(out, y, w) * w.sum()  # weighted correct count
            return carry, (loss, correct, w.sum())

        _, (losses, corrects, counts) = jax.lax.scan(step, (), (ex, ey, ew))
        total = jnp.maximum(counts.sum(), 1.0)
        return {
            "acc": corrects.sum() / total,
            "loss_sum": losses.sum(),            # P1 flavour (summed batch losses)
            "loss_mean": losses.mean(),          # P2 flavour (mean per batch)
            "count": total,
        }

    return evaluate


def make_stacked_evaluator(apply_fn):
    """Evaluate every worker's params on the same (replicated) eval stack."""
    ev = make_evaluator(apply_fn)
    return jax.vmap(lambda p, ex, ey, ew: ev(p, ex, ey, ew),
                    in_axes=(0, None, None, None))
