"""Regression tests for corrected-head (idiomatic) bf16 training.

The corrected-head objective (logits + softmax-CE, faithful=False) has
~17x larger gradients than the reference's double-softmax objective at
matched init, which puts the reference lr at the edge of stability —
where bf16 rounding noise tips whole runs into collapse (measured
run-to-run final-acc scatter 0.3-0.97 on the headline workload before
the fix; results/bench_idiomatic.json after).  Two defences are pinned
here:

* per-worker global-norm gradient clipping (OptimizerConfig.clip_norm)
* the f32 logits layer on the corrected head (zoo._ReferenceCNN)

The reference has neither knob (no clipping anywhere, SURVEY §2.1), so
both are off/inert on the faithful oracle path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                         ModelConfig, OptimizerConfig)
from dopt.optim import clip_by_global_norm, clip_by_global_norm_stacked


def _tree(seed, w=None):
    rng = np.random.default_rng(seed)
    shape = lambda *s: ((w,) + s) if w else s  # noqa: E731
    return {
        "a": jnp.asarray(rng.normal(size=shape(4, 3)), jnp.float32),
        "b": {"c": jnp.asarray(rng.normal(size=shape(5,)), jnp.float32)},
    }


def _gnorm(t):
    return float(sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(t)) ** 0.5)


def test_clip_noop_below_threshold():
    g = _tree(0)
    clipped = clip_by_global_norm(g, 1e6)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(clipped)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_clip_scales_to_max_norm():
    g = _tree(1)
    clipped = clip_by_global_norm(g, 0.5)
    assert abs(_gnorm(clipped) - 0.5) < 1e-5
    # direction preserved
    ga, ca = jax.tree.leaves(g)[0], jax.tree.leaves(clipped)[0]
    np.testing.assert_allclose(np.asarray(ca) / np.asarray(ga),
                               _gnorm(clipped) / _gnorm(g), rtol=1e-5)


def test_clip_stacked_matches_vmapped_per_worker_clip():
    g = _tree(2, w=6)
    stacked = clip_by_global_norm_stacked(g, 0.7)
    vmapped = jax.vmap(lambda t: clip_by_global_norm(t, 0.7))(g)
    for a, b in zip(jax.tree.leaves(stacked), jax.tree.leaves(vmapped)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_clip_stacked_is_per_worker_not_global():
    # One huge worker must not shrink the others' gradients.
    g = {"a": jnp.stack([jnp.ones(4) * 1000.0, jnp.ones(4) * 0.01])}
    clipped = clip_by_global_norm_stacked(g, 1.0)
    assert abs(float(jnp.linalg.norm(clipped["a"][0])) - 1.0) < 1e-5
    np.testing.assert_allclose(np.asarray(clipped["a"][1]),
                               np.full(4, 0.01), rtol=1e-6)


def _idiomatic_cfg(**opt):
    return ExperimentConfig(
        name="bf16-idiomatic-reg", seed=2028,
        data=DataConfig(dataset="synthetic", num_users=6, iid=False,
                        shards=2, synthetic_train_size=768,
                        synthetic_test_size=256, plan_impl="numpy"),
        model=ModelConfig(model="model1", input_shape=(8, 8, 1),
                          faithful=False, compute_dtype="bfloat16"),
        optim=OptimizerConfig(lr=0.1, momentum=0.5, **opt),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="stochastic", rounds=10, local_ep=2,
                            local_bs=32),
    )


@pytest.mark.slow  # ~110s: 10 bf16 rounds; the heaviest single compile
def test_idiomatic_bf16_trains_with_clip(devices):
    """Corrected-head Model1 in bf16 under clip reaches >=0.95 synthetic
    accuracy — the canary for the instability fixed in round 5 (without
    clip this config's full-scale twin scatters 0.3-0.97; the TPU-scale
    evidence is results/bench_idiomatic.json, 3 consecutive runs)."""
    from dopt.engine import GossipTrainer

    tr = GossipTrainer(_idiomatic_cfg(clip_norm=1.0), eval_every=10**6)
    tr.run(rounds=40, block=10)
    acc = float(tr.evaluate()["acc"].mean())
    assert acc >= 0.95, f"idiomatic bf16 fleet acc {acc:.3f} < 0.95"


def test_clip_config_plumbs_through_engine(devices):
    """clip_norm reaches the step core: one round with a tiny clip must
    move params less than one with no clip."""
    from dopt.engine import GossipTrainer

    def delta(clip):
        tr = GossipTrainer(_idiomatic_cfg(clip_norm=clip), eval_every=10**6)
        p0 = jax.tree.map(lambda p: np.asarray(p).copy(), tr.params)
        tr.run(rounds=1, block=1)
        return sum(float(((np.asarray(a) - b) ** 2).sum())
                   for a, b in zip(jax.tree.leaves(tr.params),
                                   jax.tree.leaves(p0))) ** 0.5

    assert delta(1e-3) < 0.1 * delta(0.0)
