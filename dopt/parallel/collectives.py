"""Worker-axis collectives: gossip mixing and federated aggregation.

This module is the TPU-native replacement for the reference's implicit
"communication layer" (SURVEY §2.4): the server handing state_dict
copies to clients (``servers.py:59-64``) and ``Simulator.Neighbors``
passing live state_dict references between peers
(``simulators.py:91-97`` + ``clients.py:61-69``).

Two execution paths for the consensus step  x_i ← Σ_j W_ij x_j :

* ``mix_dense`` — one ``tensordot`` of the [n, n] mixing matrix against
  the stacked [W, ...] pytree, written in the global view.  Under jit
  with the worker axis sharded, XLA's SPMD partitioner lowers this to
  ``all_gather`` over ICI + a local contraction — the right choice for
  complete/random/arbitrary graphs (the matrix is data, not code).
* ``mix_shifts_shardmap`` — explicit ``shard_map`` + ``lax.ppermute``
  per circulant diagonal of W (from ``dopt.topology.shift_decomposition``).
  For banded topologies (ring, dynamic single-edge) this moves only the
  neighbor shards that are actually needed: O(k·|θ|) bytes over ICI
  instead of O(n·|θ|) for the all_gather, where k = number of nonzero
  diagonals (ring: 2).

``masked_average`` is the federated path: uniform state averaging over
the sampled-client set (``servers.py:42-48``) as one weighted
reduce-sum over the worker axis, with partial participation as a 0/1
mask instead of Python-side client selection.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dopt.parallel.mesh import WORKER_AXIS, compat_shard_map


def mix_dense(stacked, w_matrix, mesh: Mesh | None = None,
              comm_dtype=None):
    """x_i ← Σ_j W_ij x_j for every leaf of a stacked [W, ...] pytree.

    Global-view formulation; XLA inserts the collectives when the worker
    axis is sharded.  ``w_matrix`` may be [n, n] or a scalar-weighted
    stack already selected for the round.  Pass ``mesh`` to pin the
    output back onto the worker axis (XLA otherwise may choose to
    replicate the contraction result).

    ``comm_dtype`` (e.g. ``jnp.bfloat16``) is WIRE-ONLY compression:
    shards are narrowed just for the cross-device gather (halving
    ICI/DCN bytes at bf16) and everything else stays exact — the mixing
    matrix remains float32 (bf16 would break row-stochasticity by
    ~1e-3/row and compound over rounds) and the accumulation runs in
    float32.  Requires ``mesh`` (without a mesh nothing crosses a wire,
    so there is nothing to compress — it raises to avoid a silent
    no-op)."""
    w = jnp.asarray(w_matrix, dtype=jnp.float32)
    if comm_dtype is not None:
        if mesh is None:
            raise ValueError("comm_dtype compression requires a mesh")
        return _mix_dense_compressed(stacked, w, mesh, comm_dtype)

    def mix_leaf(x):
        y = jnp.tensordot(w.astype(x.dtype), x, axes=[[1], [0]])
        y = y.astype(x.dtype)
        if mesh is not None:
            from dopt.parallel.mesh import worker_sharding

            y = jax.lax.with_sharding_constraint(y, worker_sharding(mesh))
        return y

    # dopt_mix scope: phase attribution for the profiler's
    # conv/comm/update split (dopt.utils.profiling.classify_phase).
    with jax.named_scope("dopt_mix"):
        return jax.tree.map(mix_leaf, stacked)


def _mix_dense_compressed(stacked, w, mesh: Mesh, comm_dtype):
    """Wire-only compressed dense mixing as an explicit shard_map: each
    device all-gathers the OTHER workers' shards at ``comm_dtype`` (the
    only bytes that cross ICI/DCN), then contracts its f32 mixing-matrix
    rows against the f32-upcast gather — exact W, f32 accumulation,
    narrow wire."""
    from dopt.parallel.mesh import worker_axes

    ax = worker_axes(mesh)

    def per_device(wr, xl):
        # wr: [W/D, W] f32 rows; xl: [W/D, ...] local worker shard.
        xg = jax.lax.all_gather(xl.astype(comm_dtype), ax, axis=0,
                                tiled=True)
        y = jnp.tensordot(wr, xg.astype(jnp.float32), axes=[[1], [0]])
        return y.astype(xl.dtype)

    def mix_leaf(x):
        fn = compat_shard_map(per_device, mesh=mesh,
                              in_specs=(P(ax, None), P(ax)),
                              out_specs=P(ax))
        return fn(w, x)

    return jax.tree.map(mix_leaf, stacked)


def _shift_plan(shift_ids, lanes: int, num_devices: int):
    """Static routing plan for the folded shift path.

    Returns ``(plan, ship)`` where ``plan[k] = (q0, q1, r)`` decomposes
    global shift ``shift_ids[k]`` into its device rotations and lane
    offset, and ``ship[q]`` is the sorted list of source lanes that must
    actually travel for nonzero rotation q — the union over consuming
    shifts, NOT the whole lane block.  A straddling ring shift (r ≠ 0)
    needs only ``lanes − r`` lanes from rotation q and ``r`` from q+1,
    so e.g. the 32-worker ring on 8 devices ships 2 lane-shards per
    device per round instead of 8 full blocks.

    Contiguity invariant used by ``mix_shifts``: every consumer needs a
    contiguous lane range [a, b), and since ship[q] ⊇ [a, b) is a sorted
    list of distinct lanes, that range occupies contiguous positions in
    the shipped block.
    """
    plan: list[tuple[int, int, int]] = []
    need: dict[int, set[int]] = {}
    for s in shift_ids:
        q, r = divmod(int(s), lanes)
        q0, q1 = q % num_devices, (q + 1) % num_devices
        plan.append((q0, q1, r))
        if r == 0:
            if q0 != 0:
                need.setdefault(q0, set()).update(range(lanes))
        else:
            if q0 != 0:
                need.setdefault(q0, set()).update(range(r, lanes))
            if q1 != 0:
                need.setdefault(q1, set()).update(range(r))
    ship = {q: sorted(v) for q, v in need.items()}
    return plan, ship


def device_rotations(shift_ids, lanes: int, num_devices: int) -> tuple[int, ...]:
    """The nonzero device-level ring rotations (one ``lax.ppermute``
    each) the folded shift path needs for a global circulant shift set:
    shift s = q·lanes + r touches rotation q (and q+1 when r ≠ 0)."""
    _, ship = _shift_plan(shift_ids, lanes, num_devices)
    return tuple(sorted(ship))


def shift_comm_lanes(shift_ids, lanes: int, num_devices: int) -> int:
    """Total worker-lane shards each device ships per ``mix_shifts``
    call — the shift path's ICI byte cost in units of |θ|-sized lanes,
    which the engine's 'auto' heuristic compares against the dense
    all_gather's (n − lanes) remote lanes per device."""
    _, ship = _shift_plan(shift_ids, lanes, num_devices)
    return sum(len(v) for v in ship.values())


def mix_shifts(stacked, shift_ids, coeff_table, mesh: Mesh, comm_dtype=None):
    """Explicit ICI path: x_i ← Σ_s coeff_s[i] · x_{(i+s) mod n}.

    ``shift_ids`` is the STATIC tuple of circulant shifts (compiled into
    the program); ``coeff_table`` is the per-round [k, n] float32
    coefficient DATA (``dopt.topology.coeffs_for_matrix``), so
    time-varying schedules and dropout-repaired matrices reuse one
    compiled step.

    Workers fold onto devices in L = n / mesh.size contiguous lanes
    (worker i = device i//L, lane i%L — the ``shard_worker_tree``
    layout).  The [n, n] circulant then decomposes into DEVICE-level
    ring rotations plus a static lane slice: global shift s = q·L + r
    needs lanes r..L-1 from device d+q and, when r ≠ 0, lanes 0..r-1
    from device d+q+1.  Each nonzero rotation is ONE ``lax.ppermute``
    carrying only the union of lanes its consumers need (``_shift_plan``)
    — a folded ring ships 2 single-lane shards per device per round
    (e.g. 32 workers on a v5e-8, SURVEY §7's "cores=8, workers_per_core=4"
    plan) instead of the dense path's (n − L)-lane all_gather.  L = 1
    degenerates to the classic one-rotation-per-shift ring schedule.
    """
    D = mesh.size
    shift_ids = tuple(int(s) for s in shift_ids)
    coeff_table = jnp.asarray(coeff_table, dtype=jnp.float32)
    n = coeff_table.shape[1]
    if n % D:
        raise ValueError(f"{n} workers do not fold onto {D} devices evenly")
    L = n // D
    plan, ship = _shift_plan(shift_ids, L, D)
    # Shipped-block bookkeeping: lane a of rotation q sits at position
    # pos[q][a] in that rotation's payload; contiguous source ranges
    # stay contiguous (see _shift_plan docstring).
    pos = {q: {lane: i for i, lane in enumerate(lanes_q)}
           for q, lanes_q in ship.items()}

    def per_device(coeffs, x):
        # x: [L, ...] local lane block; coeffs: [k, L] this block's weights.
        # comm_dtype narrows the payload only for the ppermute hops (the
        # bytes on the wire); lane values that never cross a wire (the
        # q == 0 contributions, incl. the shift-0 self term) stay exact,
        # and accumulation stays at the leaf dtype.
        xc = x.astype(comm_dtype) if comm_dtype is not None else x
        blocks = {}
        for q, lanes_q in ship.items():
            payload = xc if len(lanes_q) == L else xc[np.asarray(lanes_q)]
            perm = [((d + q) % D, d) for d in range(D)]
            blocks[q] = jax.lax.ppermute(payload, WORKER_AXIS,
                                         perm).astype(x.dtype)

        def part(q, a, b):
            """Lanes [a, b) sourced from rotation q (0 = local/exact)."""
            if q == 0:
                return x[a:b]
            p = pos[q][a]
            return blocks[q][p:p + (b - a)]

        acc = jnp.zeros_like(x)
        for k, (q0, q1, r) in enumerate(plan):
            if r == 0:
                contrib = part(q0, 0, L)
            else:
                contrib = jnp.concatenate([part(q0, r, L), part(q1, 0, r)],
                                          axis=0)
            c = coeffs[k].reshape((L,) + (1,) * (x.ndim - 1)).astype(x.dtype)
            acc = acc + c * contrib
        return acc

    coeff_specs = P(None, WORKER_AXIS)  # [k, n] -> coeffs sharded on worker axis

    def mix_leaf(x):
        fn = compat_shard_map(
            per_device,
            mesh=mesh,
            in_specs=(coeff_specs, P(WORKER_AXIS)),
            out_specs=P(WORKER_AXIS),
        )
        return fn(coeff_table, x)

    with jax.named_scope("dopt_mix"):
        return jax.tree.map(mix_leaf, stacked)


def mix_shifts_shardmap(stacked, shifts, mesh: Mesh, comm_dtype=None):
    """``mix_shifts`` with the shifts-and-coefficients pairing of
    ``dopt.topology.shift_decomposition`` (``[(shift, coeffs[n]), ...]``)
    — the single-matrix convenience form."""
    return mix_shifts(stacked, [s for s, _ in shifts],
                      jnp.asarray([c for _, c in shifts], dtype=jnp.float32),
                      mesh, comm_dtype)


def where_mask(mask, a, b):
    """Per-worker select over stacked pytrees: mask[i] ? a_i : b_i.
    Used for client-sampling (federated) and worker-dropout (gossip)
    participation masks."""
    def sel(x, y):
        m = mask.reshape((-1,) + (1,) * (x.ndim - 1)).astype(bool)
        return jnp.where(m, x, y)
    return jax.tree.map(sel, a, b)


def masked_average(stacked, mask, mesh: Mesh | None = None, comm_dtype=None):
    """Uniform average of the masked workers' states, replicated back to
    every worker: theta ← Σ_i m_i x_i / Σ_i m_i  (reference
    ``average_weights``, servers.py:42-48, with client sampling as data).

    Returns a pytree WITHOUT the worker axis (the global model).

    ``comm_dtype`` (requires ``mesh``) is wire-only compression of the
    aggregation, mirroring ``mix_dense``: each device reduces its local
    lanes at full precision, only the per-device PARTIAL sums cross the
    wire at the narrow dtype (one psum), and the final divide runs at
    the leaf dtype."""
    m = jnp.asarray(mask, dtype=jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    if comm_dtype is not None:
        if mesh is None:
            raise ValueError("comm_dtype compression requires a mesh")
        return _masked_average_compressed(stacked, m, denom, mesh, comm_dtype)

    def avg_leaf(x):
        mm = m.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return (x * mm).sum(axis=0) / denom.astype(x.dtype)

    with jax.named_scope("dopt_mix"):
        return jax.tree.map(avg_leaf, stacked)


def mean_weight_matrix(mask):
    """The masked-mean reduce as a [W, W] contraction matrix: every row
    is mask / max(Σ mask, 1), so W_mean @ X computes ``masked_average``
    broadcast back over the worker axis (each output row is the same
    global mean).  An all-dead mask yields the zero matrix — the
    contraction contributes nothing and the caller's passthrough term
    keeps theta.  Feeds the fused epilogue (``dopt.ops.fused_mix_update``
    under ``FederatedConfig.fused_update="on"``), which needs the mean
    expressed as a mixing-matrix contraction over the flat buckets."""
    m = jnp.asarray(mask, dtype=jnp.float32).reshape(-1)
    denom = jnp.maximum(m.sum(), 1.0)
    return jnp.broadcast_to(m / denom, (m.shape[0], m.shape[0]))


def _masked_average_compressed(stacked, m, denom, mesh: Mesh, comm_dtype):
    """Wire-only compressed federated reduce: each device sums its local
    lanes at full precision, the narrow PARTIAL sums are all-gathered
    (the only bytes on the wire), and the cross-device accumulation runs
    in float32 locally — so exactly one quantization per partial, never
    a narrow-dtype summation chain that would grow error with device
    count (mirrors ``_mix_dense_compressed``'s semantics)."""
    from dopt.parallel.mesh import worker_axes

    ax = worker_axes(mesh)

    def avg_leaf(x):
        def per_device(mask_l, x_l):
            mm = mask_l.reshape((-1,) + (1,) * (x_l.ndim - 1))
            part = (x_l.astype(jnp.float32) * mm).sum(axis=0)
            parts = jax.lax.all_gather(part.astype(comm_dtype), ax)
            tot = parts.astype(jnp.float32).sum(axis=0)
            return (tot / denom).astype(x_l.dtype)

        # all_gather+local-sum yields a value that IS replicated but
        # can't be statically proven so (unlike psum); skip the static
        # varying-axes check for this one collective.
        fn = compat_shard_map(per_device, mesh=mesh,
                              in_specs=(P(ax), P(ax)), out_specs=P(),
                              check=False)
        return fn(m, x)

    return jax.tree.map(avg_leaf, stacked)


# ---------------------------------------------------------------------
# Sharded weight-update / consensus hot path (update_sharding="scatter")
# ---------------------------------------------------------------------
# "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
# Training" (Xu et al., arXiv:2004.13336) applied to the consensus
# round: instead of every lane's device redundantly materialising and
# post-processing the FULL |θ| during the mixing/aggregation phase, the
# parameter tree is flattened once into size-bounded f32/bf16 BUCKETS
# ([W, Fb] slabs), the cross-worker contraction runs as per-device
# partial sums + ``psum_scatter`` (each device produces only the 1/D
# shard it owns), the remaining update math runs on that shard, and ONE
# all-gather restores the full view.  Issuing the collectives bucket by
# bucket is what lets XLA's latency-hiding scheduler overlap bucket b's
# wire time with bucket b+1's compute
# (``dopt.parallel.mesh.enable_latency_hiding_scheduler``).


@dataclasses.dataclass(frozen=True)
class UpdateShardSpec:
    """Static flattening/bucketing plan for a stacked [W, ...] pytree.

    Built once at trainer construction (``make_update_shard_spec``);
    everything here is static python data so the bucket slicing compiles
    into the round program.  ``bounds`` are fold-aligned offsets into
    the zero-padded flat axis — every bucket's length divides evenly by
    ``fold`` (the mesh device count), which is what lets
    ``psum_scatter``/``all_gather`` split each bucket exactly."""

    treedef: object
    shapes: tuple[tuple[int, ...], ...]   # per-leaf shapes sans worker axis
    sizes: tuple[int, ...]
    dtype: object
    fold: int
    flat: int      # true flattened per-worker element count
    padded: int    # flat rounded up to a fold multiple
    bounds: tuple[int, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.bounds) - 1


def make_update_shard_spec(tree, *, fold: int,
                           bucket_bytes: int = 4 << 20) -> UpdateShardSpec:
    """Plan the flat bucketing of ``tree`` (a stacked [W, ...] pytree).

    ``fold`` is the shard count (mesh size) every bucket must divide by;
    ``bucket_bytes`` bounds each bucket's per-worker payload so the
    mixing collectives are issued as a pipeline of comparable chunks
    rather than one monolithic transfer.  All leaves must share one
    dtype (the engines store params/momentum at a single param_dtype) —
    mixed dtypes would force a lossy common cast, so they are rejected."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        raise ValueError("cannot bucket an empty pytree")
    dtypes = {jnp.dtype(x.dtype) for x in leaves}
    if len(dtypes) != 1:
        raise ValueError(
            f"update sharding needs a uniform leaf dtype, got {dtypes}")
    dtype = dtypes.pop()
    shapes = tuple(tuple(x.shape[1:]) for x in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    flat = int(sum(sizes))
    fold = max(int(fold), 1)
    padded = -(-flat // fold) * fold
    per_elem = dtype.itemsize
    step = max(int(bucket_bytes) // per_elem // fold, 1) * fold
    bounds = tuple(range(0, padded, step)) + (padded,)
    return UpdateShardSpec(treedef=treedef, shapes=shapes, sizes=sizes,
                           dtype=dtype, fold=fold, flat=flat,
                           padded=padded, bounds=bounds)


def stacked_to_buckets(tree, spec: UpdateShardSpec) -> list:
    """Flatten a stacked [W, ...] pytree into the spec's [W, Fb] bucket
    slabs (zero-padded tail).  The inverse is ``buckets_to_stacked`` —
    the round trip is bit-exact (pure reshape/concat/slice)."""
    leaves = jax.tree.leaves(tree)
    w = leaves[0].shape[0]
    flat = jnp.concatenate([x.reshape(w, -1) for x in leaves], axis=1)
    if spec.padded != spec.flat:
        flat = jnp.pad(flat, ((0, 0), (0, spec.padded - spec.flat)))
    return [flat[:, a:b] for a, b in zip(spec.bounds, spec.bounds[1:])]


def _flat_to_tree(flat, spec: UpdateShardSpec, lead: tuple[int, ...]):
    out, off = [], 0
    for shape, size in zip(spec.shapes, spec.sizes):
        out.append(flat[..., off:off + size].reshape(lead + shape))
        off += size
    return spec.treedef.unflatten(out)


def buckets_to_stacked(buckets: list, spec: UpdateShardSpec):
    flat = jnp.concatenate(buckets, axis=1)[:, :spec.flat]
    return _flat_to_tree(flat, spec, (flat.shape[0],))


def buckets_to_tree(buckets: list, spec: UpdateShardSpec):
    """Single (no worker axis) variant: [Fb] buckets → the θ tree."""
    flat = jnp.concatenate(buckets, axis=0)[:spec.flat]
    return _flat_to_tree(flat, spec, ())


def _require_flat_mesh(mesh: Mesh | None, what: str) -> str:
    if mesh is None:
        raise ValueError(f"{what} requires a mesh")
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"{what} runs psum_scatter over ONE worker axis; hybrid "
            f"(hosts × ici) meshes are not supported — got {mesh.shape}")
    return mesh.axis_names[0]


def mix_dense_scatter(buckets, w_matrix, mesh: Mesh):
    """Reduce-scatter formulation of ``mix_dense`` over flat buckets:
    each device contracts the mixing matrix's columns for ITS lanes
    against its local [L, Fb] slab (a partial sum of the true output for
    every worker), and one ``psum_scatter`` both completes the sum and
    hands each device exactly its own lanes' mixed rows — no device
    ever materialises the [n, Fb] gathered fleet state, and the
    per-bucket issue order gives the latency-hiding scheduler chunks to
    overlap.

    Numerics: the mixing matrix and the accumulation stay FLOAT32
    regardless of the leaf dtype.  For f32 trees that differs from
    ``mix_dense`` only by summation association (the allclose-pinned
    parity contract); for bf16 trees it is strictly MORE precise than
    the dense path, which casts the matrix to bf16 and contracts at the
    leaf dtype — so bf16 scatter-vs-dense deltas include that matrix
    quantization (~1e-3/row), not just reassociation."""
    ax = _require_flat_mesh(mesh, "update_sharding='scatter'")
    w = jnp.asarray(w_matrix, dtype=jnp.float32)

    def per_device(w_cols, x):
        # w_cols: [n, L] — this device's lanes' columns of W;
        # x: [L, Fb] local lane slab.
        part = jnp.tensordot(w_cols, x.astype(jnp.float32),
                             axes=[[1], [0]])          # [n, Fb] partial
        own = jax.lax.psum_scatter(part, ax, scatter_dimension=0,
                                   tiled=True)         # [L, Fb] mine
        return own.astype(x.dtype)

    fn = compat_shard_map(per_device, mesh=mesh,
                          in_specs=(P(None, ax), P(ax)),
                          out_specs=P(ax))
    with jax.named_scope("dopt_mix"):
        return [fn(w, b) for b in buckets]


def mix_update_scatter(stacked, arg, mesh: Mesh, spec: UpdateShardSpec,
                       shift_ids=None):
    """The engine-facing scatter-mode consensus step: flatten the
    stacked tree into the spec's buckets, mix every bucket (dense
    reduce-scatter, or the sharded circulant contraction when the
    schedule decomposed into shifts — ``mix_shifts`` over flat buckets
    ships the SAME lane unions per rotation, just as size-bounded flat
    chunks instead of per-leaf payloads), and restore the tree."""
    buckets = stacked_to_buckets(stacked, spec)
    if shift_ids is not None:
        with jax.named_scope("dopt_mix"):
            mixed = mix_shifts(buckets, shift_ids, arg, mesh)
    else:
        mixed = mix_dense_scatter(buckets, arg, mesh)
    return buckets_to_stacked(mixed, spec)


def masked_average_scatter(stacked, mask, mesh: Mesh,
                           spec: UpdateShardSpec, denom=None):
    """Sharded-update formulation of ``masked_average`` (Xu et al.,
    arXiv:2004.13336): each device reduces its local lanes' masked
    partial sum per bucket, ``psum_scatter`` leaves each device owning
    a 1/D shard of the flat sum, the aggregation update (the divide)
    runs on that shard only, and ONE tiled all-gather re-forms the
    replicated θ — instead of every device redundantly computing the
    full |θ| average.  Returns the unstacked θ tree.

    ``denom`` (optional traced scalar) overrides the divisor: the
    hierarchical-aggregation path (``dopt.population``) accumulates
    per-lane weighted sums over multiple cohort WAVES and then needs
    Σ_lanes acc / total_cohort_weight — the lane mask alone no longer
    knows the true weight, so the caller supplies it (already guarded
    against zero)."""
    ax = _require_flat_mesh(mesh, "update_sharding='scatter'")
    m = jnp.asarray(mask, dtype=jnp.float32)
    denom = (jnp.maximum(m.sum(), 1.0) if denom is None
             else jnp.asarray(denom, jnp.float32))
    buckets = stacked_to_buckets(stacked, spec)

    def per_device(mask_l, x):
        mm = mask_l.reshape((-1,) + (1,) * (x.ndim - 1))
        part = (x.astype(jnp.float32) * mm).sum(axis=0)     # [Fb] partial
        shard = jax.lax.psum_scatter(part, ax, scatter_dimension=0,
                                     tiled=True)            # [Fb/D] mine
        with jax.named_scope("dopt_update"):
            upd = (shard / denom).astype(x.dtype)           # 1/D update
        return jax.lax.all_gather(upd, ax, axis=0, tiled=True)

    # all_gather of identical shards IS replicated but cannot be
    # statically proven so — skip the varying-axes check, mirroring
    # _masked_average_compressed.
    fn = compat_shard_map(per_device, mesh=mesh,
                          in_specs=(P(ax), P(ax)), out_specs=P(),
                          check=False)
    with jax.named_scope("dopt_mix"):
        out = [fn(m, b) for b in buckets]
    return buckets_to_tree(out, spec)


# ---------------------------------------------------------------------
# Compiled-HLO collective byte accounting
# ---------------------------------------------------------------------

_HLO_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
              "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
              "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_HLO_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")

_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+|pred)\[([0-9,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _HLO_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_BYTES[dtype]
    return total


def hlo_collective_bytes(hlo_text: str) -> dict[str, int]:
    """Count the result-buffer bytes of every cross-device collective in
    a compiled HLO dump (``jit(fn).lower(...).compile().as_text()``):
    ``{op_kind: bytes, ..., "total": bytes}``.

    This is the measured basis for comm-volume claims — e.g. the folded
    shift path's "2 lane-shards per device vs the dense all_gather's
    n − L" (``tests/test_collectives.py`` pins it against the compiled
    programs, not the docstring).  Result-buffer bytes upper-bound wire
    bytes proportionally (an all-gather's result includes the local
    shard), which cancels in path-vs-path comparisons.  Async pairs
    (``*-start``/``*-done``) are counted once, at the start op."""
    out: dict[str, int] = {k: 0 for k in _HLO_COLLECTIVES}
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        rhs = line.partition("=")[2].strip()
        for kind in _HLO_COLLECTIVES:
            m = re.search(rf"(^|\s){re.escape(kind)}(-start)?\(", rhs)
            if m:
                out[kind] += _shape_bytes(rhs[:m.start()])
                break
    out["total"] = sum(out[k] for k in _HLO_COLLECTIVES)
    return out


def broadcast_to_workers(tree, num_workers: int):
    """theta → stacked [W, ...] (the server handing every client a copy
    of the global model, servers.py:63 — here a free broadcast)."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + x.shape), tree
    )


def mix_power(stacked, w_matrix, eps: int = 1, mesh: Mesh | None = None,
              comm_dtype=None):
    """eps consensus sweeps (FedLCon, simulators.py:182-212 — with the
    stale-accumulation bug fixed: each sweep reads the previous sweep's
    output).  eps=1 is plain consensus; jit at the caller."""
    if eps == 1:
        return mix_dense(stacked, w_matrix, mesh, comm_dtype)

    def body(x, _):
        return mix_dense(x, w_matrix, mesh, comm_dtype), None

    out, _ = jax.lax.scan(body, stacked, None, length=eps)
    return out
