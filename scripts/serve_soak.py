"""Serve soak: the resident trainer, end-to-end, with invariants.

The ``dopt serve`` acceptance harness — a scripted single-host
resident run (real daemon subprocesses, real signals) that survives

* a live **membership change** (leave + later rejoin through the
  control plane → the churn/shard-reassignment machinery),
* a live **config change** (an ``optim.lr`` step applied at a round
  boundary via checkpoint → rebuild → restore),
* a **SIGTERM rolling restart** (drain to the boundary → checkpoint →
  re-exec in place → resume),

and asserts the four things a resident trainer owes you:

1. **Bit-exact elasticity** — the interrupted leg's History, fault
   ledger (``control`` + ``churn`` rows included) and canonical
   telemetry stream are IDENTICAL to an uninterrupted leg driven by
   the same command schedule: zero non-ledgered divergence.
2. **Ledgered control** — every applied command appears once in the
   ledger and once as a deterministic ``control`` event, at the same
   boundary round in both legs.
3. **Stream integrity** — both metrics streams pass
   ``dopt.obs.check`` (schema + gapless duplicate-free rounds across
   the restart's segment headers).
4. **Zero false positives** — the STOCK rule set raises no alert on
   either leg, and the daemon's own in-process monitor (stock set +
   the escalated drop-rate rule) reports healthy.

Stream equality is asserted through ``python -m dopt.obs.diff`` (the
first-divergence differ this soak's inline assert grew into), so a
red run names the exact diverging event instead of "streams differ".

Two further modes:

* ``--fleet`` — a REAL 2-process ``jax.distributed`` fleet leg with a
  live membership + config change and a SIGTERM rolling restart of a
  follower; every process streams its own telemetry, and the
  ``dopt.obs.aggregate`` fleet aggregator must verify cross-process
  DETERMINISTIC_KINDS consistency through the restart, produce a
  merged stream that passes ``dopt.obs.check``, and yield an SLO
  report with finite p50/p99 for boundary-tick, command-apply,
  checkpoint-save/restore and alert latency (a sensitized drop-rate
  rule turns the membership churn into a real measured alert).
  ``--fleet`` then runs the DECOUPLED async/one-peer leg on top: two
  independent daemons on ``gossip.topology=one_peer_exp`` +
  ``gossip.mixing=async``, a mid-run SIGTERM of the rank-1 child, and
  the zero-paused-rounds assertion — the survivor's round watermark
  strictly increases through the whole restart window while the
  liveness protocol ledgers the peer's lanes leaving and rejoining.
* ``--minutes N`` — the LONG soak (the ROADMAP 1-hour item as a flag,
  not a rewrite): a resident run kept alive for N wall minutes under
  seeded randomized live churn (membership leave/join, lr and
  checkpoint-cadence config churn, SIGTERM rolling restarts on a
  timer), drained at the deadline, with the SLO latency report
  (p50/p99 per name) written to ``--slo-out``.

    python scripts/serve_soak.py --rounds 48 --min-seconds 60
    python scripts/serve_soak.py --engine federated --rounds 24
    python scripts/serve_soak.py --fleet --rounds 40 --slo-out slo.json
    python scripts/serve_soak.py --minutes 20 --slo-out slo.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dopt.serve.control import CommandQueue, make_command  # noqa: E402

# Reuse the chaos soak's ledger-invariant checker (the serve ledger
# adds fleet-level control rows, which it now accepts).
from scripts.chaos_soak import check_ledger  # noqa: E402


def serve_args(engine: str, rounds: int, seed: int,
               checkpoint_every: int) -> list[str]:
    """The CLI argv for one soak leg (tiny synthetic workload — the
    soak exercises the runtime, not the model)."""
    preset = "baseline1" if engine == "gossip" else "baseline3"
    args = ["--preset", preset, "--num-users", "8",
            "--max-rounds", str(rounds),
            "--checkpoint-every", str(checkpoint_every),
            "--set", "seed=%d" % seed,
            "--set", "data.dataset=synthetic",
            "--set", "data.synthetic_train_size=256",
            "--set", "data.synthetic_test_size=64",
            "--set", "model.model=mlp",
            "--set", "model.faithful=false"]
    if engine == "gossip":
        args += ["--set", "gossip.local_ep=1", "--set", "gossip.local_bs=32"]
    else:
        args += ["--set", "federated.local_ep=1",
                 "--set", "federated.local_bs=32"]
    return args


def seed_commands(state_dir: Path, rounds: int) -> dict[str, int]:
    """The scripted command schedule, pinned to round boundaries so
    both legs apply identically: leave at ~N/4, lr step at ~N/2,
    rejoin at ~5N/8."""
    marks = {"leave": max(rounds // 4, 1),
             "lr": max(rounds // 2, 2),
             "join": max(5 * rounds // 8, 3)}
    q = CommandQueue(state_dir / "commands.jsonl")
    q.submit(make_command("membership", worker=3, action="leave",
                          at_round=marks["leave"], id="soak-leave"))
    q.submit(make_command("config", key="optim.lr", value=0.05,
                          at_round=marks["lr"], id="soak-lr"))
    q.submit(make_command("membership", worker=3, action="join",
                          at_round=marks["join"], id="soak-join"))
    return marks


def run_leg(name: str, state_dir: Path, argv: list[str], *,
            on_term: str, kill_at: int | None = None,
            timeout_s: float = 900.0) -> dict:
    """Run one daemon subprocess to drain; with ``kill_at``, SIGTERM it
    once the status file reports that round (the daemon drains to the
    boundary, checkpoints, re-execs IN PLACE — same pid — and resumes
    to the configured max)."""
    state_dir.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, "-m", "dopt.serve", *argv,
           "--state-dir", str(state_dir), "--on-term", on_term]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    t0 = time.time()
    proc = subprocess.Popen(cmd, env=env, cwd=REPO)
    status_path = state_dir / "serve.json"
    killed = False
    while True:
        try:
            rc = proc.wait(timeout=0.5)
            break
        except subprocess.TimeoutExpired:
            pass
        if time.time() - t0 > timeout_s:
            proc.kill()
            raise AssertionError(f"[{name}] leg timed out after "
                                 f"{timeout_s:.0f}s")
        if kill_at is not None and not killed and status_path.exists():
            try:
                st = json.loads(status_path.read_text())
            except ValueError:
                continue
            if st.get("status") == "serving" and st.get("round", 0) \
                    >= kill_at:
                print(f"[{name}] SIGTERM at round {st['round']} "
                      f"(pid {proc.pid}) -> rolling restart", flush=True)
                os.kill(proc.pid, signal.SIGTERM)
                killed = True
    elapsed = time.time() - t0
    assert rc == 0, f"[{name}] daemon exited rc={rc}"
    if kill_at is not None:
        assert killed, (f"[{name}] never reached round {kill_at} to "
                        "deliver the SIGTERM")
    final = json.loads((state_dir / "final.json").read_text())
    if kill_at is not None:
        assert final.get("restarts", 0) >= 1, \
            f"[{name}] daemon drained without surviving a restart"
    print(f"[{name}] drained at round {final['round']} in {elapsed:.1f}s "
          f"(restarts={final.get('restarts', 0)})", flush=True)
    final["_elapsed_s"] = elapsed
    return final


def check_streams(path_a: Path, path_b: Path, rounds: int) -> None:
    from dopt.obs import HealthMonitor, JsonlSink, canonical, check_stream
    from dopt.obs.diff import diverge_canonical, format_divergence

    ev_a = JsonlSink.read(path_a)
    ev_b = JsonlSink.read(path_b)
    sa, sb = check_stream(ev_a), check_stream(ev_b)
    assert sa["rounds"] == sb["rounds"] == rounds, (sa, sb)
    assert sb["segments"] >= sa["segments"] + 1, \
        "restarted leg should carry at least one extra segment header"
    # The first-divergence differ IS the equality assert now: a red
    # run names the exact diverging canonical event.  The CLI form
    # (`python -m dopt.obs.diff A B`) is the same code path.
    ca = canonical(ev_a)
    div = diverge_canonical(ca, canonical(ev_b))
    assert div is None, "canonical streams diverged between legs:\n" \
        + format_divergence(str(path_a), str(path_b), div)
    n_ctl = sum(1 for e in ca if e["kind"] == "control")
    assert n_ctl == 3, f"expected 3 applied control events, saw {n_ctl}"
    print(f"[streams] canonical equality ok: {sa['events']} vs "
          f"{sb['events']} events, {n_ctl} control events each", flush=True)
    # Zero false positives under the STOCK rule set, on both legs.
    for name, evs in (("uninterrupted", ev_a), ("restarted", ev_b)):
        mon = HealthMonitor()
        mon.feed(evs)
        rep = mon.report()
        assert rep.alerts == 0 and rep.verdict == "healthy", \
            (f"false-positive gate: {name} leg raised {rep.alerts} "
             f"alerts: {mon.canonical_alerts()}")
    print("[streams] zero stock-rule alerts on both legs", flush=True)


# Sensitized monitor rule set for the latency-measuring legs: a
# drop-rate instance tight enough that the scripted membership churn
# fires a REAL warn alert through the real in-process path — which is
# what makes `alert_latency` a measured number instead of an empty
# histogram.  (The daemon always appends its escalated
# drop_rate_critical auto-pause rule on top; 0.02 << 0.5 never
# triggers the pause.)
SENSITIZED_RULES = [{"rule": "drop_rate", "max_rate": 0.02,
                     "window": 4, "min_rounds": 2}]

# The SLO names the fleet/long legs must report finite p50/p99 for
# (dopt.obs.latency.SLO_LATENCIES, restated here so the soak fails
# loudly if the contract drifts).
SLO_CORE = ("boundary_tick", "command_apply", "checkpoint_save",
            "checkpoint_restore")


def write_slo_report(path: str, payload: dict) -> None:
    from dopt.utils.metrics import atomic_write_text

    atomic_write_text(path, json.dumps(payload, indent=2))
    print(f"wrote SLO report to {path}", flush=True)
    for name, s in sorted(payload.get("slo", {}).items()):
        print(f"[slo] {name}: n={s['count']} p50={s['p50']}s "
              f"p99={s['p99']}s max={s['max']}s", flush=True)


def assert_slo(slo: dict, names) -> None:
    for name in names:
        s = slo.get(name)
        assert s and s["count"] >= 1, \
            f"SLO report misses latency {name!r}: {sorted(slo)}"
        for q in ("p50", "p99"):
            v = s.get(q)
            assert isinstance(v, (int, float)), \
                f"SLO {name}.{q} not finite: {s}"


def sigterm_child(state_dir: Path, process_id: int) -> bool:
    """SIGTERM one fleet child by its --process-id (the rolling-restart
    trigger).  No leading dashes in the pgrep pattern — it would parse
    them as its own options."""
    out = subprocess.run(
        ["pgrep", "-f", f"state-dir {state_dir}.*process-id "
                        f"{process_id}"],
        capture_output=True, text=True)
    pids = [int(p) for p in out.stdout.split()]
    if not pids:
        return False
    os.kill(pids[0], signal.SIGTERM)
    return True


def run_fleet_soak(args, root: Path) -> int:
    """The 2-process fleet leg: real ``jax.distributed`` + gloo, live
    membership + config change, SIGTERM rolling restart of a follower —
    then the fleet aggregator must verify cross-process consistency,
    its merged stream must pass ``dopt.obs.check``, and the SLO report
    must carry finite p50/p99 for every core latency plus
    alert_latency."""
    from dopt.obs import JsonlSink, summarize_latency_events
    from dopt.utils.metrics import atomic_write_text

    state = root / "fleet"
    if state.exists():
        import shutil

        shutil.rmtree(state)
    state.mkdir(parents=True)
    rounds = args.rounds
    marks = seed_commands(state, rounds)
    kill_at = max(3 * rounds // 8, 2)
    rules_file = root / "fleet-rules.json"
    atomic_write_text(rules_file, json.dumps(SENSITIZED_RULES))

    cmd = [sys.executable, "-m", "dopt.serve",
           *serve_args(args.engine, rounds, args.seed,
                       args.checkpoint_every),
           "--state-dir", str(state), "--rules-file", str(rules_file),
           "--num-processes", "2", "--devices-per-proc", "2"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    print(f"[fleet] engine={args.engine} rounds={rounds} commands at "
          f"{marks}, follower SIGTERM at >= {kill_at}", flush=True)
    t0 = time.time()
    sup = subprocess.Popen(cmd, env=env, cwd=REPO)
    status_path = state / "serve.json"
    killed = False
    timeout_s = 1500.0
    while sup.poll() is None:
        time.sleep(0.2)
        if time.time() - t0 > timeout_s:
            sup.kill()
            raise AssertionError(f"[fleet] timed out after {timeout_s}s")
        if killed or not status_path.exists():
            continue
        try:
            st = json.loads(status_path.read_text())
        except ValueError:
            continue
        if st.get("status") == "serving" \
                and kill_at <= st.get("round", 0) <= rounds - 4:
            killed = sigterm_child(state, 1)
            if killed:
                print(f"[fleet] SIGTERM follower at round {st['round']} "
                      "-> rolling restart", flush=True)
    rc = sup.wait()
    assert rc == 0, f"[fleet] supervisor exited rc={rc} " \
                    f"(logs in {state / 'logs'})"
    assert killed, f"[fleet] never caught the fleet inside the " \
                   f"SIGTERM window (>= {kill_at})"
    final = json.loads((state / "final.json").read_text())
    assert final["round"] == rounds and final.get("restarts", 0) >= 1, \
        {k: final.get(k) for k in ("round", "restarts")}
    rep = final.get("report") or {}
    assert rep.get("verdict") in ("healthy", "warn"), rep
    assert rep.get("alerts", 0) >= 1, \
        "sensitized drop_rate rule never fired — alert_latency " \
        "unmeasured"

    # Cross-process DETERMINISTIC_KINDS consistency through the
    # rolling restart, via the product's own aggregator CLI.
    merged_path = state / "merged.jsonl"
    rc = subprocess.run(
        [sys.executable, "-m", "dopt.obs.aggregate",
         "--state-dir", str(state), "--processes", "2",
         "--merged-out", str(merged_path)], cwd=REPO).returncode
    assert rc == 0, "fleet aggregator found cross-process divergence"
    rc = subprocess.run(
        [sys.executable, "-m", "dopt.obs.check", str(merged_path),
         "--state-dir", str(state)], cwd=REPO).returncode
    assert rc == 0, "merged / per-process streams failed dopt.obs.check"
    print("[fleet] aggregator consistency + merged stream check ok",
          flush=True)

    merged = JsonlSink.read(merged_path)
    procs = {e.get("process") for e in merged if e.get("kind") == "latency"}
    assert procs == {0, 1}, \
        f"expected latency events from both processes, saw {procs}"
    slo = summarize_latency_events(merged)
    assert_slo(slo, SLO_CORE + ("alert_latency",))
    payload = {"mode": "fleet", "engine": args.engine, "rounds": rounds,
               "restarts": final.get("restarts"),
               "alerts": rep.get("alerts"), "verdict": rep.get("verdict"),
               "elapsed_s": round(time.time() - t0, 1), "slo": slo,
               "final_slo": final.get("slo")}
    if args.slo_out:
        write_slo_report(args.slo_out, payload)
    print("fleet soak passed: 2-process fleet with rolling restart, "
          "cross-process deterministic consistency verified, merged "
          "stream checked, SLO p50/p99 finite for "
          f"{', '.join(SLO_CORE + ('alert_latency',))}", flush=True)
    return 0


def sigterm_decoupled_child(state_dir: Path, rank: int) -> bool:
    """SIGTERM one decoupled-fleet child by its state subdir (no
    leading dashes in the pgrep pattern)."""
    out = subprocess.run(
        ["pgrep", "-f", f"state-dir {state_dir}/p{rank} "],
        capture_output=True, text=True)
    pids = [int(p) for p in out.stdout.split()]
    if not pids:
        return False
    os.kill(pids[0], signal.SIGTERM)
    return True


def run_decoupled_soak(args, root: Path) -> int:
    """The async/one-peer DECOUPLED fleet leg (``--fleet`` runs it
    after the SPMD leg): two independent daemons on
    ``gossip.topology=one_peer_exp`` + ``gossip.mixing=async``, SIGTERM
    the rank-1 child mid-run, and assert the tentpole property — ZERO
    PAUSED ROUNDS: the survivor's round watermark strictly increases
    through the entire SIGTERM → re-exec → resume window (an SPMD
    fleet's survivor freezes in a collective there until the whole
    generation respawns).  Also asserts the restarted child resumed
    (restarts >= 1, stream passes ``dopt.obs.check``) and that the
    liveness protocol ledgered the peer's leave AND rejoin on the
    survivor before the drain."""
    state = root / "decoupled"
    if state.exists():
        import shutil

        shutil.rmtree(state)
    state.mkdir(parents=True)
    # Event-driven, not round-budgeted: the restarted child pays a
    # fresh python + jax re-init before its heartbeat returns, and on
    # slow CI that can outlast any fixed round count.  So the fleet
    # runs with an effectively unbounded round cap, the harness waits
    # for each phase (restart window closed, rejoin ledgered on the
    # survivor) and then drains everyone by SIGTERM — the 1500s
    # ceiling is the only clock.
    kill_at = 8
    base = serve_args("gossip", 100000, args.seed,
                      args.checkpoint_every)
    cmd = [sys.executable, "-m", "dopt.serve", *base,
           "--set", "gossip.topology=one_peer_exp",
           "--set", "gossip.mixing=async",
           "--state-dir", str(state), "--no-admin",
           "--num-processes", "2", "--decoupled", "--peer-timeout", "5"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    print(f"[decoupled] one_peer_exp+async, rank-1 SIGTERM at >= "
          f"{kill_at}, drain once the rejoin lands", flush=True)
    t0 = time.time()
    sup = subprocess.Popen(cmd, env=env, cwd=REPO)
    status_path = state / "p0" / "serve.json"

    def watermark() -> int | None:
        try:
            st = json.loads(status_path.read_text())
        except (OSError, ValueError):
            return None
        return (int(st["round"])
                if st.get("status") == "serving" else None)

    def peer_live() -> dict:
        try:
            return json.loads((state / "liveness-p1.json").read_text())
        except (OSError, ValueError):
            return {}

    def ledgered(action: str) -> set[int]:
        workers = set()
        try:
            lines = (state / "p0" / "applied.jsonl").read_text()
        except OSError:
            return workers
        for line in lines.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("status") == "applied" \
                    and rec.get("cmd") == "membership" \
                    and rec.get("action") == action:
                workers.add(int(rec.get("worker", -1)))
        return workers

    killed = False
    killed_pid = None
    samples: list[int] = []
    window_open = False
    drained = False
    timeout_s = 1500.0
    while sup.poll() is None:
        time.sleep(0.1)
        if time.time() - t0 > timeout_s:
            sup.kill()
            raise AssertionError(f"[decoupled] timed out after "
                                 f"{timeout_s}s")
        w = watermark()
        if not killed:
            if w is not None and w >= kill_at:
                killed_pid = peer_live().get("pid")
                killed = sigterm_decoupled_child(state, 1)
                if killed:
                    window_open = True
                    print(f"[decoupled] SIGTERM rank 1 (pid "
                          f"{killed_pid}) at survivor round {w}",
                          flush=True)
            continue
        if window_open:
            if w is not None and (not samples or w != samples[-1]):
                samples.append(w)
            live = peer_live()
            # The window spans SIGTERM → drain → respawn → re-init:
            # closed only when a DIFFERENT pid heartbeats "serving"
            # (the old pid keeps a stale "serving" stamp until its
            # drain boundary rewrites it).
            if live.get("status") == "serving" \
                    and live.get("pid") not in (None, killed_pid) \
                    and samples:
                window_open = False
                print(f"[decoupled] rank 1 back (pid {live.get('pid')},"
                      f" round {live.get('round')}); survivor "
                      f"watermark through the window: {samples}",
                      flush=True)
            continue
        if not drained and ledgered("join") == {4, 5, 6, 7}:
            # Rejoin ledgered on the survivor: the protocol completed
            # a full leave → restart → rejoin cycle; drain everyone.
            drained = True
            print("[decoupled] rejoin ledgered on survivor; draining "
                  "fleet", flush=True)
            os.kill(sup.pid, signal.SIGTERM)
    rc = sup.wait()
    assert rc == 0, f"[decoupled] supervisor exited rc={rc} " \
                    f"(logs in {state / 'logs'})"
    assert killed, "[decoupled] fleet never reached the SIGTERM round"
    assert not window_open, \
        "[decoupled] rank 1 never came back serving before the fleet " \
        "drained"
    assert drained, \
        "[decoupled] rank 1's lanes never rejoined on the survivor " \
        f"(applied joins: {sorted(ledgered('join'))})"
    assert all(b > a for a, b in zip(samples, samples[1:])), \
        f"[decoupled] survivor watermark went backwards: {samples}"
    assert len(samples) >= 3, \
        f"[decoupled] survivor advanced only {samples} while rank 1 " \
        "was down — the restart PAUSED the fleet"
    assert ledgered("leave") == {4, 5, 6, 7}, \
        "[decoupled] survivor never ledgered rank 1's lanes away"

    finals = {}
    for rank in (0, 1):
        finals[rank] = json.loads(
            (state / f"p{rank}" / "final.json").read_text())
        assert finals[rank]["round"] >= kill_at, \
            (rank, finals[rank]["round"])
        crc = subprocess.run(
            [sys.executable, "-m", "dopt.obs.check",
             str(state / f"p{rank}" / "metrics.jsonl"),
             "--state-dir", str(state / f"p{rank}")],
            cwd=REPO).returncode
        assert crc == 0, f"[decoupled] p{rank} stream failed " \
                         "dopt.obs.check"
    assert finals[1].get("restarts", 0) >= 1, finals[1].get("restarts")
    assert finals[0].get("restarts", 0) == 0, finals[0].get("restarts")
    from dopt.utils.metrics import atomic_write_text

    atomic_write_text(state / "decoupled-report.json", json.dumps({
        "mode": "decoupled",
        "survivor_watermark": samples,
        "final_rounds": {r: finals[r]["round"] for r in (0, 1)},
        "restarts_p1": finals[1].get("restarts"),
        "elapsed_s": round(time.time() - t0, 1)}, indent=2))
    print("decoupled soak passed: one_peer_exp+async fleet trained "
          "straight through a peer's SIGTERM restart — survivor "
          f"watermark {samples} (zero paused rounds), peer resumed "
          f"after {finals[1].get('restarts')} restart(s), lanes left "
          "and rejoined via liveness", flush=True)
    return 0


def run_long_soak(args, root: Path) -> int:
    """``--minutes N``: the ROADMAP long soak.  One resident daemon
    kept alive for N wall minutes under seeded randomized churn —
    membership leave/join, lr + checkpoint-cadence config churn,
    SIGTERM rolling restarts — then drained; the SLO latency report
    (p50/p99 per name) is the artifact."""
    import random

    from dopt.obs import JsonlSink, check_stream, summarize_latency_events
    from dopt.serve.control import CommandQueue, make_command
    from dopt.utils.metrics import atomic_write_text

    rng = random.Random(args.seed)
    state = root / "long"
    if state.exists():
        import shutil

        shutil.rmtree(state)
    state.mkdir(parents=True)
    rules_file = root / "long-rules.json"
    atomic_write_text(rules_file, json.dumps(SENSITIZED_RULES))
    cmd = [sys.executable, "-m", "dopt.serve",
           *serve_args(args.engine, 10**9, args.seed,
                       args.checkpoint_every),
           "--state-dir", str(state), "--rules-file", str(rules_file),
           "--on-term", "restart", "--no-admin"]
    # serve_args pins --max-rounds; strip it — the long soak runs on
    # wall time and drains through the control plane.
    i = cmd.index("--max-rounds")
    del cmd[i:i + 2]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    deadline = time.time() + args.minutes * 60.0
    t0 = time.time()
    proc = subprocess.Popen(cmd, env=env, cwd=REPO)
    q = CommandQueue(state / "commands.jsonl")
    away: set[int] = set()
    n_cmd = n_restart = 0
    next_cmd = time.time() + args.churn_period
    next_restart = time.time() + max(args.churn_period * 3, 30.0)
    status_path = state / "serve.json"
    print(f"[long] {args.minutes:.1f} min of randomized churn "
          f"(seed {args.seed}, command every ~{args.churn_period:.0f}s)",
          flush=True)
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"[long] daemon exited rc={proc.returncode} "
                    "mid-soak")
            time.sleep(1.0)
            now = time.time()
            if now >= next_cmd:
                next_cmd = now + args.churn_period * (0.5 + rng.random())
                kind = rng.choice(("membership", "lr", "cadence",
                                   "checkpoint"))
                n_cmd += 1
                cid = f"churn-{n_cmd}"
                if kind == "membership":
                    if away and (len(away) >= 3 or rng.random() < 0.5):
                        w = rng.choice(sorted(away))
                        away.discard(w)
                        q.submit(make_command("membership", worker=w,
                                              action="join", id=cid))
                    else:
                        w = rng.choice([i for i in range(1, 8)
                                        if i not in away])
                        away.add(w)
                        q.submit(make_command("membership", worker=w,
                                              action="leave", id=cid))
                elif kind == "lr":
                    q.submit(make_command(
                        "config", key="optim.lr",
                        value=round(0.05 + 0.1 * rng.random(), 4),
                        id=cid))
                elif kind == "cadence":
                    q.submit(make_command(
                        "config", key="checkpoint_every",
                        value=rng.choice((4, 8, 12)), id=cid))
                else:
                    q.submit(make_command("checkpoint", id=cid))
            if now >= next_restart and status_path.exists() \
                    and deadline - now > 45.0:
                # Leave headroom before the drain: a SIGTERM racing the
                # deadline would lose its boundary to the drain command
                # and count a restart that never happened.
                next_restart = now + max(args.churn_period * 3, 30.0)
                try:
                    st = json.loads(status_path.read_text())
                except ValueError:
                    continue
                if st.get("status") == "serving" and st.get("pid"):
                    n_restart += 1
                    print(f"[long] SIGTERM at round {st.get('round')} "
                          f"(restart {n_restart})", flush=True)
                    try:
                        os.kill(int(st["pid"]), signal.SIGTERM)
                    except OSError:
                        pass
        q.submit(make_command("drain", id="long-drain"))
        rc = proc.wait(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == 0, f"[long] daemon exited rc={rc}"
    elapsed = time.time() - t0
    final = json.loads((state / "final.json").read_text())
    rep = final.get("report") or {}
    assert rep.get("verdict") in ("healthy", "warn"), rep
    events = JsonlSink.read(state / "metrics.jsonl")
    summary = check_stream(events)
    print(f"[long] drained at round {final['round']} after "
          f"{elapsed / 60:.1f} min: {n_cmd} commands, {n_restart} "
          f"SIGTERM restarts (survived {final.get('restarts')}), "
          f"{summary['segments']} stream segments, verdict "
          f"{rep.get('verdict')}", flush=True)
    assert final.get("restarts", 0) >= min(n_restart, 1), final.get(
        "restarts")
    slo = summarize_latency_events(events)
    core = [n for n in SLO_CORE
            if n != "checkpoint_restore" or n_restart > 0
            or "checkpoint_restore" in slo]
    assert_slo(slo, core)
    if rep.get("alerts", 0) >= 1:
        assert_slo(slo, ("alert_latency",))
    payload = {"mode": "long", "engine": args.engine,
               "minutes": args.minutes, "rounds": final["round"],
               "commands": n_cmd, "sigterm_restarts": n_restart,
               "restarts": final.get("restarts"),
               "alerts": rep.get("alerts"),
               "verdict": rep.get("verdict"),
               "segments": summary["segments"],
               "elapsed_s": round(elapsed, 1), "slo": slo,
               "final_slo": final.get("slo")}
    if args.slo_out:
        write_slo_report(args.slo_out, payload)
    print("long soak passed: resident through randomized live + config "
          "churn, stream integrity intact, SLO latencies measured",
          flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=48)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--engine", choices=("gossip", "federated"),
                    default="gossip")
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--min-seconds", type=float, default=0.0,
                    help="assert the restarted leg stayed resident at "
                         "least this long (the ROADMAP's >=60s soak bar)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the 2-process fleet leg instead: rolling "
                         "restart + aggregator consistency + merged-"
                         "stream check + SLO report")
    ap.add_argument("--minutes", type=float, default=None, metavar="N",
                    help="LONG-soak mode: keep one resident run alive "
                         "for N wall minutes under seeded randomized "
                         "live churn + config churn, then drain and "
                         "report SLO latencies (the ROADMAP 1-hour "
                         "soak is --minutes 60)")
    ap.add_argument("--churn-period", type=float, default=20.0,
                    help="long-soak mean seconds between randomized "
                         "commands (restarts fire every ~4 periods)")
    ap.add_argument("--slo-out", default=None, metavar="PATH",
                    help="write the SLO latency report (p50/p99 per "
                         "latency name) here (fleet/long modes)")
    ap.add_argument("--state-root", default=None,
                    help="scratch root (default: a temp dir)")
    ap.add_argument("--report-out", default=None, metavar="PATH",
                    help="write both legs' final reports as one JSON "
                         "artifact here")
    args = ap.parse_args(argv)

    import tempfile

    # Resolved: the daemon subprocess runs with cwd=REPO, so a relative
    # --state-root would otherwise name a different directory for the
    # harness and the daemon.
    root = Path(args.state_root
                or tempfile.mkdtemp(prefix="dopt-soak-")).resolve()
    if args.minutes is not None:
        return run_long_soak(args, root)
    if args.fleet:
        rc = run_fleet_soak(args, root)
        if rc == 0:
            # The zero-paused-rounds leg rides the same flag: the SPMD
            # fleet proves bit-exact quiesce-and-resume, the decoupled
            # fleet proves training THROUGH the restart.
            rc = run_decoupled_soak(args, root)
        return rc
    rounds = args.rounds
    attempt = 0
    dir_a = root / "uninterrupted"
    while True:
        base = serve_args(args.engine, rounds, args.seed,
                          args.checkpoint_every)
        kill_at = max(3 * rounds // 8, 2)
        if dir_a.exists():
            import shutil

            shutil.rmtree(dir_a)
        marks_a = seed_commands(dir_a, rounds)
        print(f"[soak] engine={args.engine} rounds={rounds} "
              f"commands at {marks_a}, SIGTERM at >= {kill_at}", flush=True)
        final_a = run_leg("uninterrupted", dir_a, base, on_term="drain")
        # Self-calibration: round throughput varies 10x across CI
        # hardware, and the bar is RESIDENT SECONDS, not rounds —
        # rescale and redo the reference leg until it clears the bar
        # with margin (the restarted leg only ever runs longer: it
        # pays the re-exec warmup on top).
        if args.min_seconds <= 0 \
                or final_a["_elapsed_s"] >= args.min_seconds * 1.1:
            break
        scale = max(2, int(args.min_seconds * 1.3
                           // max(final_a["_elapsed_s"], 1.0)) + 1)
        rounds *= scale
        attempt += 1
        assert attempt <= 3, "soak calibration did not converge"
        print(f"[soak] {final_a['_elapsed_s']:.1f}s < "
              f"{args.min_seconds:.0f}s bar: rescaling to {rounds} "
              "rounds", flush=True)

    dir_b = root / "restarted"
    if dir_b.exists():
        # A persistent --state-root may hold a previous invocation's
        # leg: resuming its drained state would end immediately and
        # fail the comparison with a misleading message.
        import shutil

        shutil.rmtree(dir_b)
    marks_b = seed_commands(dir_b, rounds)
    assert marks_a == marks_b
    final_b = run_leg("restarted", dir_b, base, on_term="restart",
                      kill_at=kill_at)

    assert final_b["history"] == final_a["history"], \
        "History diverged between uninterrupted and restarted legs"
    assert final_b["fault_ledger"] == final_a["fault_ledger"], \
        "fault ledger diverged between uninterrupted and restarted legs"
    rows = final_a["fault_ledger"]
    check_ledger_rows = [r for r in rows]

    class _H:  # check_ledger wants a History-shaped object
        faults = check_ledger_rows

    n = check_ledger(_H, rounds, 8)
    kinds = sorted({r["kind"] for r in rows})
    assert "control" in kinds and "churn" in kinds, kinds
    print(f"[ledger] {n} rows identical across legs, kinds {kinds}",
          flush=True)

    check_streams(dir_a / "metrics.jsonl", dir_b / "metrics.jsonl",
                  rounds)

    for name, final in (("uninterrupted", final_a), ("restarted", final_b)):
        rep = final.get("report") or {}
        assert rep.get("verdict") == "healthy", \
            f"{name} leg's in-process monitor: {rep}"
    print("[monitor] in-process verdicts healthy on both legs", flush=True)

    if args.min_seconds > 0:
        assert final_b["_elapsed_s"] >= args.min_seconds, \
            (f"restarted leg stayed resident only "
             f"{final_b['_elapsed_s']:.1f}s < {args.min_seconds:.0f}s — "
             "raise --rounds")

    if args.report_out:
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(args.report_out, json.dumps({
            "engine": args.engine, "rounds": rounds,
            "commands": marks_a, "kill_at": kill_at,
            "uninterrupted": {k: v for k, v in final_a.items()
                              if k not in ("history", "fault_ledger")},
            "restarted": {k: v for k, v in final_b.items()
                          if k not in ("history", "fault_ledger")},
        }, indent=2))
        print(f"wrote soak report to {args.report_out}", flush=True)

    print("serve soak passed: live membership + config change + SIGTERM "
          "rolling restart with bit-exact resume, zero non-ledgered "
          "divergence, zero false-positive alerts", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
