"""Model zoo: param-count parity with the reference, head semantics, loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.models import build_model, count_params
from dopt.models.losses import accuracy, cross_entropy, l2_regulariser


def _init(model, shape):
    return model.init(jax.random.key(0), jnp.zeros((1, *shape)))["params"]


def test_model1_param_count_parity():
    # Reference models.py:5 comment — 1,663,370 params, arithmetic verified.
    params = _init(build_model("model1"), (28, 28, 1))
    assert count_params(params) == 1_663_370


def test_model3_param_count_parity():
    # Reference models.py:30 comment — 1,105,098 params.
    params = _init(build_model("model3", num_classes=10), (32, 32, 3))
    assert count_params(params) == 1_105_098


def test_faithful_returns_probabilities():
    m = build_model("model1", faithful=True)
    params = _init(m, (28, 28, 1))
    out = m.apply({"params": params}, jnp.ones((4, 28, 28, 1)))
    np.testing.assert_allclose(np.sum(out, axis=-1), 1.0, rtol=1e-5)
    assert np.all(out >= 0)


def test_corrected_head_returns_logits():
    m = build_model("model1", faithful=False)
    params = _init(m, (28, 28, 1))
    out = m.apply({"params": params}, jnp.ones((4, 28, 28, 1)))
    assert not np.allclose(np.sum(out, axis=-1), 1.0)


def test_double_softmax_loss_differs_from_corrected():
    # The faithful objective is NOT the standard CE — make sure we are
    # really reproducing the reference's bug.
    logits = jnp.array([[2.0, -1.0, 0.5]])
    labels = jnp.array([0])
    corrected = cross_entropy(logits, labels)
    faithful = cross_entropy(jax.nn.softmax(logits), labels)
    assert abs(float(corrected) - float(faithful)) > 0.1


def test_cross_entropy_weighted_mask():
    out = jnp.array([[5.0, 0.0], [0.0, 5.0], [9.9, 9.9]])
    y = jnp.array([0, 1, 0])
    w = jnp.array([1.0, 1.0, 0.0])
    full = cross_entropy(out[:2], y[:2])
    masked = cross_entropy(out, y, w)
    np.testing.assert_allclose(float(full), float(masked), rtol=1e-6)


def test_accuracy_mask():
    out = jnp.array([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    y = jnp.array([0, 1, 1])
    assert float(accuracy(out, y)) == pytest.approx(2 / 3)
    assert float(accuracy(out, y, jnp.array([1.0, 1.0, 0.0]))) == pytest.approx(0.5)


def test_mlp_and_logistic():
    m = build_model("mlp", faithful=False)
    p = _init(m, (28, 28, 1))
    assert m.apply({"params": p}, jnp.ones((2, 28, 28, 1))).shape == (2, 10)
    lr = build_model("logistic", num_classes=2, faithful=False)
    plr = _init(lr, (123,))
    assert lr.apply({"params": plr}, jnp.ones((2, 123))).shape == (2, 2)
    assert count_params(plr) == 123 * 2 + 2
    assert float(l2_regulariser(plr, 0.0)) == 0.0


def test_resnet18_forward():
    m = build_model("resnet18", faithful=False)
    p = _init(m, (32, 32, 3))
    n = count_params(p)
    assert 10_000_000 < n < 12_000_000, n  # ~11.2M standard ResNet-18
    out = m.apply({"params": p}, jnp.ones((2, 32, 32, 3)))
    assert out.shape == (2, 10)


def test_build_model_unknown():
    with pytest.raises(ValueError, match="unknown model"):
        build_model("model2")


def test_faithful_conv_stack_has_no_activations():
    # The reference conv block is conv->pool->conv->pool with NO ReLU
    # (models.py:10-15); a linear conv stack commutes with scaling.
    import jax
    import jax.numpy as jnp
    m = build_model("model1", faithful=True)
    p = m.init(jax.random.key(1), jnp.zeros((1, 28, 28, 1)))["params"]

    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 28, 28, 1)), jnp.float32)
    # Idiomatic variant with the SAME params gives different outputs
    # (ReLU between convs) — guards against silently re-adding conv ReLUs.
    m2 = build_model("model1", faithful=False)
    out1 = m.apply({"params": p}, x)
    out2 = m2.apply({"params": p}, x)
    assert not np.allclose(np.asarray(out1), np.asarray(jax.nn.softmax(out2)), atol=1e-4)


def test_bf16_compute_mode_trains():
    # bf16 compute, fp32 params: forward emits reasonable values and a
    # short training run still learns on the virtual mesh.
    import jax
    import jax.numpy as jnp

    from dopt.models import build_model

    m = build_model("model1", dtype="bfloat16", faithful=False)
    params = m.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))["params"]
    # params stay fp32 (bf16 is compute-only)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))
    out = m.apply({"params": params}, jnp.ones((2, 28, 28, 1)))
    # Corrected head: the logits layer computes in f32 even under bf16
    # compute (raw-logit CE is bf16-noise-sensitive; see zoo.py), so
    # the output dtype is float32.
    assert out.dtype == jnp.float32 and out.shape == (2, 10)

    import dataclasses

    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    cfg = ExperimentConfig(
        name="bf16", seed=5,
        data=DataConfig(dataset="synthetic", num_users=4,
                        synthetic_train_size=512, synthetic_test_size=128),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False, compute_dtype="bfloat16"),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=4, local_ep=1,
                            local_bs=32),
    )
    tr = GossipTrainer(cfg)
    h = tr.run(rounds=4, block=2)
    accs = [r["avg_test_acc"] for r in h.rows if "avg_test_acc" in r]
    assert accs[-1] > 0.6, accs


def test_max_pool_first_winner_tie_gradients_match_torch():
    """The reshape-max pool's custom VJP must route tie gradients to the
    FIRST window element in kernel scan order, exactly like torch's
    MaxPool2d backward — ties are common on real data (zero-background
    MNIST under the faithful no-ReLU conv gives exact 4-way bias ties
    in every background window, ADVICE r4)."""
    torch = pytest.importorskip("torch")

    from dopt.models.zoo import _max_pool_2x2

    rng = np.random.default_rng(0)
    # Quantised values force plenty of exact ties, including all-equal
    # windows; a zero block models MNIST background.
    x = rng.integers(-2, 3, size=(2, 8, 8, 3)).astype(np.float32)
    x[0, :4, :4, :] = 0.0
    # Weighted sum output so the upstream gradient is non-uniform.
    gw = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)

    gj = jax.grad(
        lambda a: jnp.sum(_max_pool_2x2(a) * gw))(jnp.asarray(x))

    xt = torch.tensor(np.moveaxis(x, -1, 1), requires_grad=True)  # NCHW
    out = torch.nn.functional.max_pool2d(xt, 2, 2)
    out.backward(torch.tensor(np.moveaxis(gw, -1, 1)))
    gt = np.moveaxis(xt.grad.numpy(), 1, -1)

    np.testing.assert_array_equal(np.asarray(gj), gt)


def test_stacked_cnn_apply_non_square_input():
    """The grouped-stacked CNN forward must handle non-square inputs
    (fc1's VALID-conv kernel reshape derives H'/W' from the activation
    shape, not a square-root guess — ADVICE r4)."""
    from dopt.models import make_stacked_apply

    m = build_model("model1", faithful=False)
    shape = (12, 8, 1)
    p1 = _init(m, shape)
    stacked = jax.tree.map(lambda a: jnp.stack([a, a]), p1)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 3, *shape)), jnp.float32)
    out = make_stacked_apply(m)(stacked, x)
    assert out.shape == (2, 3, 10)
    ref = m.apply({"params": p1}, x[0])
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_resnet_stage_sizes_override():
    """stage_sizes builds shallow ResNet variants (dryrun/test trims) and
    is rejected for non-resnet models."""
    m = build_model("resnet18", stage_sizes=(1, 1))
    p = _init(m, (8, 8, 1))
    blocks = [k for k in p if k.startswith("ResidualBlock")]
    assert len(blocks) == 2, blocks
    out = m.apply({"params": p}, jnp.zeros((2, 8, 8, 1)))
    assert out.shape == (2, 10)
    with pytest.raises(ValueError, match="resnet18 only"):
        build_model("mlp", stage_sizes=(1, 1))
