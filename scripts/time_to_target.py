"""Wall-clock-to-target-accuracy meter runs (BASELINE.json north-star
metric: "wall-clock to 90% test acc").

Runs baseline2 (16-worker D-SGD, CIFAR CNN) and baseline5 (32-worker
gossip ResNet-18) in throughput trim (bfloat16 compute, native batch
planner, fused round blocks, eval every round) until the fleet-mean
test accuracy crosses the target or the preset's round budget runs out,
then reports the time-to-target via ``dopt.utils.metrics.time_to_target``.

Data note: this environment has no network egress, so the runs use the
deterministic SYNTHETIC dataset at CIFAR scale — the artifact records
that explicitly.  Absolute accuracies are not comparable to real
CIFAR-10; the meter, cadence, and wall-clock accounting are exactly
what a real-data run would use (drop raw CIFAR under DOPT_DATA_DIR and
re-run).  seconds_per_round comes from steady-state blocks (the first,
compile-carrying block is excluded and reported separately).

Usage: python scripts/time_to_target.py [--target 0.9] [--quick]
Writes results/time_to_target.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_preset(name: str, *, target: float, quick: bool,
               block: int = 5) -> dict:
    from dopt.engine import GossipTrainer
    from dopt.presets import get_preset
    from dopt.utils.metrics import time_to_target

    cfg = get_preset(name)
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, compute_dtype="bfloat16"),
        data=dataclasses.replace(cfg.data, plan_impl="native"),
    )
    budget = 20 if quick else cfg.gossip.rounds
    trainer = GossipTrainer(cfg, eval_every=1)

    block_times: list[tuple[int, float]] = []
    done = 0
    reached_at = None
    while done < budget:
        k = min(block, budget - done)
        t0 = time.perf_counter()
        trainer.run(rounds=k, block=k)
        block_times.append((k, time.perf_counter() - t0))
        done += k
        accs = [r.get("avg_test_acc") for r in trainer.history.rows]
        if any(a is not None and a >= target for a in accs):
            reached_at = next(i for i, a in enumerate(accs)
                              if a is not None and a >= target)
            break

    # Steady-state seconds/round: exclude the compile-carrying first
    # block; fall back to the overall mean if only one block ran.
    if len(block_times) > 1:
        steady = block_times[1:]
        sec_per_round = sum(t for _, t in steady) / sum(k for k, _ in steady)
    else:
        sec_per_round = block_times[0][1] / block_times[0][0]

    meter = time_to_target(trainer.history, target=target,
                           seconds_per_round=sec_per_round)
    accs = [r.get("avg_test_acc") for r in trainer.history.rows
            if r.get("avg_test_acc") is not None]
    return {
        "preset": name,
        "model": cfg.model.model,
        "workers": cfg.data.num_users,
        "data": f"synthetic ({cfg.data.dataset}-scale; no egress — real "
                "data via DOPT_DATA_DIR)",
        "target_acc": target,
        "time_to_target": meter,
        "seconds_per_round_steady": round(sec_per_round, 4),
        "first_block_seconds_incl_compile": round(block_times[0][1], 2),
        "rounds_run": done if reached_at is None else reached_at + 1,
        "final_acc": round(accs[-1], 4) if accs else None,
        "best_acc": round(max(accs), 4) if accs else None,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=float, default=0.9)
    ap.add_argument("--quick", action="store_true",
                    help="cap at 20 rounds per preset (machinery check)")
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--out", default="results/time_to_target.json")
    args = ap.parse_args()

    names = args.only or ["baseline2", "baseline5"]
    results = [run_preset(n, target=args.target, quick=args.quick)
               for n in names]
    for r in results:
        m = r["time_to_target"]
        status = (f"reached at round {m['round']} "
                  f"(~{m['seconds']:.1f}s)" if m["reached"]
                  else f"not reached in {r['rounds_run']} rounds "
                       f"(best {r['best_acc']})")
        print(f"{r['preset']}: target {r['target_acc']} {status} "
              f"[{r['seconds_per_round_steady']*1e3:.0f} ms/round steady]")

    import jax

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(
        {"suite": "time_to_target", "device": str(jax.devices()[0]),
         "results": results}, indent=2) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
