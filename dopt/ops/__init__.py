from dopt.ops.fused_update import (
    fused_mix_sgd,
    fused_mix_update,
    fused_sgd_momentum,
    fused_sgd_momentum_tree,
    mix_sgd_reference,
    pallas_available,
)

__all__ = [
    "fused_mix_sgd",
    "fused_mix_update",
    "fused_sgd_momentum",
    "fused_sgd_momentum_tree",
    "mix_sgd_reference",
    "pallas_available",
]
