"""The resident trainer daemon (``python -m dopt.serve``).

``ServeDaemon`` owns a training loop indefinitely instead of for
``--rounds N``: the engines' ``run_served`` entry calls back into
``boundary()`` before every round, where the daemon

1. **ingests** new control-plane commands (``dopt.serve.control``) and
   applies the due ones — membership join/leave through the
   ``MembershipLog`` → churn/shard-reassignment machinery, whitelisted
   config changes through checkpoint → rebuild → restore, cadence /
   pause / drain in place — each application ledgered as a
   ``control`` fault-ledger row AND a deterministic ``control``
   telemetry event at the boundary round;
2. **checkpoints** on a round cadence (and at every boundary that
   applied a command, so the applied ledger never gets ahead of the
   training state) through the existing atomic size-manifest format;
3. **watches itself**: the PR 10 ``HealthMonitor`` rides the telemetry
   fan-out IN-PROCESS (no file tailing), its state checkpointed next
   to the trainer so a restarted daemon resumes the rule windows
   mid-stream, and a ``drop_rate``-critical alert auto-pauses
   admission (join commands are rejected until a ``resume``);
4. **survives restarts**: SIGTERM → drain to the boundary →
   checkpoint → hand back for re-exec → bit-exact resume.  The run is
   a pure function of (base config, applied-command ledger), so an
   interrupted-and-resumed serve produces History, fault ledger and
   canonical telemetry identical to an uninterrupted one.

Multi-process fleets (real ``jax.distributed`` process groups — the
grown-up ``scripts/multiprocess_demo.py``) run one daemon per process:
process 0 is the **leader** (owns the queue, telemetry, admin
endpoint, checkpoint writes), followers replay the leader's published
per-boundary directive so every process applies the same commands at
the same round — the coordinator-led config/epoch barrier.  Fleet
checkpoints cross-process-allgather the sharded state (a collective
every process joins) with a single writer.  A SIGTERM to ANY process
requests a rolling restart: the fleet quiesces at the next boundary,
checkpoints once, every process re-execs, and training resumes
bit-exactly on a fresh coordinator — SPMD collectives make per-host
independence cooperative, so "one host at a time" means the run
survives each host's restart in turn, not that collectives proceed
through it.

**Decoupled fleets** (``--decoupled``) kill that round barrier: each
process is an independent single-host daemon (its own state subdir,
queue, ledger, checkpoints — its own leader), and NO collective spans
processes, so a departing peer cannot quiesce anyone.  Liveness rides
per-process heartbeat files (``liveness-p<rank>.json`` in the shared
fleet dir, refreshed at every boundary and stamped ``draining``/
``restarting`` on the way out): each daemon folds peer liveness into
its OWN ``MembershipLog`` at each boundary — a peer gone (stale
heartbeat or an explicit drain stamp) auto-``leave``s that peer's lane
range, the existing churn repair degrades those mixing rows to
identity (with ``topology='one_peer_exp'`` + ``mixing='async'`` the
survivors' mix is pure self-weight — no wire to the missing peer),
and a fresh heartbeat auto-``join``s the lanes back.  A SIGTERM'd
peer drains to its boundary, checkpoints, exits ``EX_RESTART``; the
supervisor respawns ONLY that child and it resumes bit-exactly —
survivors never stop ticking: a rolling restart with zero paused
rounds.  The liveness-driven auto rows are wall-clock-scheduled
(WHICH boundary sees a peer away depends on timing), so unlike every
other ledger row they are not bit-reproducible across runs; each
process's canonical stream remains self-consistent and replayable
(the rows land in the ledger like any commanded transition).  Each
daemon still simulates the full lane fleet locally (peers' lanes are
frozen by the away mask, not computed remotely) — decoupled mode is
the control-plane half of decentralization; cross-host lane exchange
stays with the SPMD fleet path.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Any

from dopt.serve.control import (CommandQueue, ControlLedger,
                                apply_config_change, applied_record,
                                control_event_fields, control_ledger_row,
                                make_command, replay_effects)

# Exit code meaning "re-exec me" (BSD EX_TEMPFAIL — the conventional
# try-again code): the supervisor (or the shell loop in the README)
# respawns the daemon with the same state dir and it resumes.
EX_RESTART = 75

_STATUS_FILE = "serve.json"
_FINAL_FILE = "final.json"
_MONITOR_FILE = "monitor.json"
_COMMANDS_FILE = "commands.jsonl"
_APPLIED_FILE = "applied.jsonl"
_METRICS_FILE = "metrics.jsonl"
_CKPT_DIR = "ckpt"
_EPOCH_DIR = "epoch"
_RESTART_FLAG = "restart-requested"
_LIVENESS_PREFIX = "liveness-p"


def build_serve_trainer(cfg, membership):
    """Construct the engine for a served run with the membership
    overlay armed (the elastic program compiles up front — a later
    join/leave never retraces)."""
    if cfg.backend != "jax" or cfg.seqlm is not None:
        raise ValueError(
            "dopt serve drives the federated/gossip jax engines only "
            "(the torch oracle and the seqlm engine have no serve "
            "entry)")
    from dopt.engine import FederatedTrainer, GossipTrainer

    if cfg.federated is not None:
        return FederatedTrainer(cfg, membership=membership)
    return GossipTrainer(cfg, membership=membership)


class _LockedPrometheusSink:
    """PrometheusSink behind an RLock: the admin thread renders while
    the training thread emits."""

    def __init__(self):
        from dopt.obs.sinks import PrometheusSink

        self._prom = PrometheusSink()
        self._lock = threading.RLock()

    def emit(self, event):
        with self._lock:
            self._prom.emit(event)

    def emit_many(self, events):
        with self._lock:
            for ev in events:
                self._prom.emit(ev)

    def render(self) -> str:
        with self._lock:
            return self._prom.render()

    def close(self):
        pass


def serve_rules(extra_drop_rate: float = 0.5, specs=None):
    """The daemon's monitor rule set: ``default_rules()`` — or, with
    ``specs`` (the ``build_rules`` list shape a ``--rules-file`` JSON
    carries), the operator's declarative set instead — plus an
    ESCALATED drop-rate instance at critical severity, ALWAYS appended:
    that is the signal the admission auto-pause keys on, and a rule
    swap must not silently disarm it.  The escalation threshold (lost
    contributions per participant-round) is far above anything a
    healthy fleet produces, so the clean-run false-positive gate still
    holds."""
    from dopt.obs.rules import DropRateRule, build_rules, default_rules

    rules = build_rules(specs) if specs is not None else default_rules()
    esc = DropRateRule(max_rate=float(extra_drop_rate), window=4,
                       min_rounds=2)
    esc.name = "drop_rate_critical"
    esc.severity = "critical"
    rules.append(esc)
    return rules


class ServeDaemon:
    """One resident trainer + its control plane.  ``start()`` builds
    (or resumes) everything, ``serve()`` runs until drained or told to
    restart; the instance itself is the ``run_served`` controller."""

    def __init__(self, cfg, state_dir, *, checkpoint_every: int = 8,
                 max_rounds: int | None = None, on_term: str = "restart",
                 admin_host: str = "127.0.0.1",
                 admin_port: int | None = None,
                 rules=None, process_id: int = 0, num_processes: int = 1,
                 directive_poll_s: float = 0.05,
                 directive_max_polls: int = 12000,
                 fleet_rank: int = 0, fleet_size: int = 1,
                 fleet_dir=None, peer_timeout_s: float = 10.0):
        if on_term not in ("restart", "drain"):
            raise ValueError(
                f"on_term must be 'restart' or 'drain', got {on_term!r}")
        if int(fleet_size) > 1 and int(num_processes) > 1:
            raise ValueError(
                "a decoupled fleet (fleet_size > 1) and an SPMD fleet "
                "(num_processes > 1) are mutually exclusive: decoupled "
                "daemons are independent single-process leaders")
        self.base_cfg = cfg
        self.cfg = cfg
        self.state_dir = Path(state_dir)
        self.checkpoint_every = int(checkpoint_every)
        self.max_rounds = max_rounds
        self.on_term = on_term
        self.admin_host = admin_host
        self.admin_port = admin_port
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.is_leader = self.process_id == 0
        self._rules = rules
        self._directive_poll_s = float(directive_poll_s)
        self._directive_max_polls = int(directive_max_polls)
        # Decoupled-fleet identity: rank within the fleet of
        # independent daemons, and the SHARED parent dir carrying every
        # process's liveness heartbeat.  SPMD fleets share state_dir,
        # so the default fleet_dir covers them too (the leader's
        # heartbeat is what _await_directive's timeout reports).
        self.fleet_rank = int(fleet_rank)
        self.fleet_size = int(fleet_size)
        self.fleet_dir = (Path(fleet_dir) if fleet_dir is not None
                          else self.state_dir)
        self.peer_timeout_s = float(peer_timeout_s)
        self._decoupled = self.fleet_size > 1
        self._liveness_rank = (self.fleet_rank if self._decoupled
                               else self.process_id)

        self.queue = CommandQueue(self.state_dir / _COMMANDS_FILE)
        self.ledger = ControlLedger(self.state_dir / _APPLIED_FILE)
        self.ckpt_path = self.state_dir / _CKPT_DIR
        # EVERY process streams telemetry: the leader to metrics.jsonl,
        # followers to metrics-p<i>.jsonl — followers replay the
        # leader's directives, so the deterministic kinds of all N
        # streams must be bit-identical, which is exactly what the
        # fleet aggregator (dopt.obs.aggregate) verifies.
        self.metrics_path = self.state_dir / (
            _METRICS_FILE if self.process_id == 0
            else f"metrics-p{self.process_id}.jsonl")

        self.trainer = None
        self.telemetry = None
        self.monitor = None
        self.prom = None
        self.admin = None
        self.membership = None
        self.paused = False
        self.restarts = 0
        self.status = "starting"
        self._pending: list[dict[str, Any]] = []
        self._processed: set[str] = set()
        self._term = False
        self._term_signal: str | None = None
        self._last_ckpt = -1
        self._alerts_seen = 0
        self._resumed = False
        # On-demand live profiling (POST /admin/profile): an armed
        # request captures a jax.profiler trace for the next K rounds
        # and writes a Chrome-trace artifact merged with the host
        # spans.  Pure observability — no ledger row, no telemetry
        # event, no training-state effect: arming it leaves History,
        # fault ledger and canonical stream bit-identical.
        self._profile_pending = 0
        self._profile: dict[str, Any] | None = None
        self._profile_artifacts: list[str] = []
        # Guards the armed/active transitions: POSTs arrive on the
        # admin's ThreadingHTTPServer threads while the serve thread
        # consumes the arm at boundaries — without it two concurrent
        # POSTs could both pass the already-armed check and both 202.
        self._profile_lock = threading.Lock()
        # Per-process boundary visit counter: a config-change rebuild
        # REVISITS the same round boundary, so directives are keyed by
        # (visit sequence, round), never round alone — SPMD lockstep
        # means every process counts visits identically, and the
        # supervisor wipes the directive dir between generations.
        self._boundary_seq = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ServeDaemon":
        self.state_dir.mkdir(parents=True, exist_ok=True)
        resume_round = self._peek_checkpoint_round()
        records = ControlLedger.replay(self.state_dir / _APPLIED_FILE)
        effects = replay_effects(
            records, up_to_round=resume_round if resume_round is not None
            else -1)
        from dopt.faults import MembershipLog

        self.membership = MembershipLog(effects["membership"])
        cfg = self.base_cfg
        for _, key, value in effects["config"]:
            cfg = apply_config_change(cfg, key, value)
        self.cfg = cfg
        if effects["checkpoint_every"] is not None:
            self.checkpoint_every = int(effects["checkpoint_every"])
        self.paused = bool(effects["paused"])
        self._processed = set(effects["processed"])
        self.restarts = int(self._read_status_field("restarts", 0))

        self.trainer = build_serve_trainer(self.cfg, self.membership)
        if not self.is_leader:
            self.trainer.checkpoint_writer = False
        restore_s: float | None = None
        if resume_round is not None:
            t0 = time.perf_counter()  # dopt: allow-wallclock -- checkpoint_restore SLO latency meter, reporting only
            self.trainer.restore(self.ckpt_path)
            restore_s = time.perf_counter() - t0  # dopt: allow-wallclock -- checkpoint_restore SLO latency meter, reporting only
            self._resumed = True
            self.restarts += 1
        self._last_ckpt = int(self.trainer.round) if self._resumed else -1

        from dopt.obs import HealthMonitor, Telemetry, attach

        self.telemetry = Telemetry.to_jsonl(self.metrics_path,
                                            resume=True)
        stream_watermark = self.telemetry.watermark
        if self.is_leader:
            self.prom = _LockedPrometheusSink()
            self.telemetry.sinks.append(self.prom)
            mon_state = None
            mpath = self.state_dir / _MONITOR_FILE
            if self._resumed and mpath.exists():
                try:
                    mon_state = json.loads(mpath.read_text())
                except ValueError:
                    mon_state = None   # torn by a hard kill: start fresh
            self.monitor = HealthMonitor(
                self._rules if self._rules is not None else serve_rules(),
                workers=self.trainer.num_workers, state=mon_state)
            self.monitor.attach(self.telemetry)
            self._alerts_seen = len(self.monitor.alerts)
        attach(self.trainer, self.telemetry,
               checkpoint_every=self.checkpoint_every or None)
        if restore_s is not None:
            self._observe_latency("checkpoint_restore", restore_s,
                                  int(self.trainer.round))
        if self._resumed and stream_watermark <= int(self.trainer.round):
            # Commands applied at EXACTLY the resume boundary may
            # have lost their control events: the event trails the
            # last sealed round, so repair_tail can drop it on
            # reopen (and a kill window can lose it outright) —
            # while one shielded by a later non-droppable event
            # (e.g. the boundary's `checkpoint`) survives.  Re-emit
            # exactly the MISSING ones, by id, so the resumed
            # stream carries each applied command once.
            r = int(self.trainer.round)
            present = self._stream_control_ids(r)
            for rec in records:
                if rec.get("status") == "applied" \
                        and int(rec.get("round", -1)) == r \
                        and str(rec.get("id")) not in present:
                    self.telemetry.emit(
                        "control",
                        **control_event_fields(
                            rec, r, auto=bool(rec.get("auto"))))
        if self.is_leader and self.admin_port is not None:
            from dopt.serve.admin import AdminServer

            self.admin = AdminServer(self, host=self.admin_host,
                                     port=self.admin_port).start()
        self._install_signals()
        self.status = "serving"
        self._write_status()
        self._write_liveness(int(self.trainer.round))
        return self

    def _stream_control_ids(self, round_idx: int) -> set[str]:
        """Ids of ``control`` events at ``round_idx`` already in the
        metrics stream (post ``repair_tail``).  One linear scan at
        startup; the substring pre-filter keeps it cheap on long
        streams."""
        ids: set[str] = set()
        if not self.metrics_path.exists():
            return ids
        with open(self.metrics_path, encoding="utf-8") as f:
            for line in f:
                if '"control"' not in line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("kind") == "control" \
                        and ev.get("round") == round_idx:
                    ids.add(str(ev.get("id")))
        return ids

    def _peek_checkpoint_round(self) -> int | None:
        """The complete checkpoint's round, or None when starting
        fresh — read via the same completeness/fallback logic a
        restore would use."""
        from dopt.utils.checkpoint import (IncompleteCheckpointError,
                                           load_checkpoint)

        if not self.ckpt_path.exists() and not self.ckpt_path.with_name(
                self.ckpt_path.name + ".old").exists():
            return None
        try:
            _, meta = load_checkpoint(self.ckpt_path)
        except IncompleteCheckpointError:
            return None
        return int(meta["round"])

    def _read_status_field(self, key: str, default):
        p = self.state_dir / _STATUS_FILE
        if not p.exists():
            return default
        try:
            return json.loads(p.read_text()).get(key, default)
        except ValueError:
            return default

    def _install_signals(self) -> None:
        def _term(signum, frame):
            self._term = True
            self._term_signal = ("drain" if signum == signal.SIGINT
                                 else self.on_term)
            if not self.is_leader:
                # A follower cannot decide for the fleet: it files a
                # stop request (carrying WHICH stop — SIGINT drains,
                # SIGTERM follows --on-term) that the leader folds
                # into the next boundary's directive.
                try:
                    (self.state_dir / _RESTART_FLAG).write_text(
                        self._term_signal)
                except OSError:
                    pass

        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)

    # -- the run_served controller ------------------------------------
    def boundary(self, trainer) -> str:
        tick0 = time.perf_counter()  # dopt: allow-wallclock -- boundary_tick SLO latency meter, reporting only
        t = int(trainer.round)
        self._boundary_seq += 1
        if self.num_processes > 1 and not self.is_leader:
            directive = self._await_directive(self._boundary_seq, t)
        else:
            directive = self._decide(t, trainer)
            if self.num_processes > 1:
                self._publish_directive(self._boundary_seq, directive)
        verdict = self._execute(directive, trainer)
        # boundary_tick measures the CONTROL-PLANE work (ingest,
        # directive, apply, checkpoint decision) — the profile tick
        # runs after the meter so a capture's artifact write never
        # skews the SLO.
        self._observe_latency(
            "boundary_tick",
            time.perf_counter() - tick0, t)  # dopt: allow-wallclock -- boundary_tick SLO latency meter, reporting only
        self._write_liveness(t)
        self._profile_tick(t, verdict)
        return verdict

    def _observe_latency(self, name: str, seconds: float,
                         round_idx: int) -> None:
        """Stream one SLO latency observation (``dopt.obs.latency``):
        a non-deterministic v1 ``latency`` event — wall durations, so
        like resource/compile it stays outside the canonical
        comparison; the in-process monitor folds it into the histogram
        the HealthReport and ``final.json`` summarize."""
        if self.telemetry is None:
            return
        self.telemetry.emit(
            "latency", round=max(int(round_idx), 0), name=str(name),
            seconds=round(max(float(seconds), 0.0), 6))  # dopt: allow-nondet-event -- SLO latency channel, documented non-deterministic like resource/compile

    def _decide(self, t: int, trainer) -> dict[str, Any]:
        """Leader: resolve this boundary completely (what applies, what
        is rejected, whether to checkpoint/stop/rebuild) so followers
        can replay the decision verbatim."""
        commands, malformed = self.queue.poll()
        for rej in malformed:
            if rej["id"] in self._processed:
                continue
            self._processed.add(rej["id"])
            self.ledger.append({"v": 1, "id": rej["id"],
                                "cmd": rej.get("cmd"),
                                "status": "rejected", "round": t,
                                "reason": rej["reason"]})
        for c in commands:
            if c["id"] not in self._processed:
                self._pending.append(c)

        due = [c for c in self._pending
               if c.get("at_round") is None or int(c["at_round"]) <= t]
        applied: list[dict[str, Any]] = []
        rejected: list[dict[str, Any]] = []
        auto_ids: list[str] = []
        stop: str | None = None
        paused = self.paused
        for c in due:
            cmd = c["cmd"]
            if cmd == "membership":
                if int(c["worker"]) >= trainer.num_workers:
                    rejected.append(applied_record(
                        c, status="rejected", round_idx=t,
                        reason=f"worker {c['worker']} outside the "
                               f"provisioned {trainer.num_workers}-lane "
                               "fleet"))
                    continue
                if c["action"] == "join" and paused:
                    rejected.append(applied_record(
                        c, status="rejected", round_idx=t,
                        reason="admission paused (resume to re-open)"))
                    continue
            if cmd == "drain":
                stop = "restart" if c.get("restart") else "drain"
            if cmd == "pause":
                paused = True
            if cmd == "resume":
                paused = False
            applied.append(c)

        # drop_rate-critical auto-pause: the monitor's alerts are
        # deterministic over the stream, so the pause lands at the same
        # boundary in an interrupted and an uninterrupted run.
        if self.monitor is not None and not paused:
            fresh = self.monitor.alerts[self._alerts_seen:]
            if any(a.get("severity") == "critical"
                   and str(a.get("rule", "")).startswith("drop_rate")
                   for a in fresh):
                c = make_command("pause", id=f"auto-pause-{t}")
                applied.append(c)
                auto_ids.append(c["id"])
        if self.monitor is not None:
            self._alerts_seen = len(self.monitor.alerts)

        # Decoupled fleets: peer liveness becomes membership here.
        # Appended AFTER the queue sweep (operator commands win the
        # boundary) and unconditionally on pause — a liveness rejoin
        # restores a provisioned peer, it does not admit a new one.
        if self._decoupled:
            for c in self._peer_transitions(t):
                applied.append(c)
                auto_ids.append(c["id"])

        if self._term:
            stop = stop or self._term_signal or self.on_term
        flag = self.state_dir / _RESTART_FLAG
        if flag.exists():
            try:
                requested = flag.read_text().strip()
            except OSError:
                requested = "restart"
            stop = stop or (requested if requested in ("restart", "drain")
                            else "restart")
        if stop is None and self.max_rounds is not None \
                and t >= int(self.max_rounds):
            stop = "drain"

        rebuild = any(c["cmd"] == "config" and c["key"] != "checkpoint_every"
                      for c in applied)
        cadence = (self.checkpoint_every and t > 0
                   and t % self.checkpoint_every == 0
                   and t != self._last_ckpt)
        checkpoint = bool(applied) or bool(cadence) or stop is not None \
            or rebuild
        if t == 0 and not applied and stop is None:
            checkpoint = False   # nothing to persist before round 0
        return {"round": t, "apply": applied, "rejected": rejected,
                "auto": auto_ids, "stop": stop, "rebuild": rebuild,
                "checkpoint": checkpoint}

    def _execute(self, directive: dict[str, Any], trainer) -> str:
        t = int(directive["round"])
        done_ids = set()
        if self.is_leader:
            for rec in directive["rejected"]:
                self.ledger.append(rec)
                self._processed.add(str(rec.get("id")))
                done_ids.add(str(rec.get("id")))
        for c in directive["apply"]:
            auto = c.get("id") in directive.get("auto", ())
            trainer.history.faults.append(control_ledger_row(c, t))
            self._install_effect(c, t)
            if self.is_leader:
                self.ledger.append(applied_record(c, status="applied",
                                                  round_idx=t, auto=auto))
                self._processed.add(str(c["id"]))
            if self.telemetry is not None:
                # EVERY process's stream carries the deterministic
                # control event (followers replay the directive, so
                # leader and follower streams must agree — the fleet
                # aggregator's consistency check).
                self.telemetry.emit(
                    "control", **control_event_fields(c, t, auto=auto))
            ets = c.get("ts")
            if isinstance(ets, (int, float)):
                # enqueue → applied: the latency an operator actually
                # waits on a command (the queue stamps `ts` at submit).
                self._observe_latency(
                    "command_apply",
                    time.time() - float(ets), t)  # dopt: allow-wallclock -- command_apply SLO latency vs the queue ts stamp, reporting only
            done_ids.add(str(c.get("id")))
        if done_ids:
            self._pending = [c for c in self._pending
                             if str(c.get("id")) not in done_ids]

        if directive["checkpoint"]:
            self._checkpoint(trainer, t)
        stop = directive["stop"]
        if stop is not None:
            self.status = ("draining" if stop == "drain" else "restarting")
        self._write_status(round_=t)
        if stop is not None:
            return stop
        if directive["rebuild"]:
            return "rebuild"
        return "run"

    def _install_effect(self, c: dict[str, Any], t: int) -> None:
        cmd = c["cmd"]
        if cmd == "config":
            if c["key"] == "checkpoint_every":
                self.checkpoint_every = int(c["value"])
            else:
                self.cfg = apply_config_change(self.cfg, c["key"],
                                               c["value"])
        elif cmd == "membership":
            self.membership.add(t, int(c["worker"]),
                                c["action"] == "join")
        elif cmd == "pause":
            self.paused = True
        elif cmd == "resume":
            self.paused = False
        # checkpoint/drain effects are carried by the directive itself.

    def _checkpoint(self, trainer, t: int) -> None:
        t0 = time.perf_counter()  # dopt: allow-wallclock -- checkpoint_save SLO latency meter, reporting only
        trainer.save(self.ckpt_path)
        if self.num_processes > 1:
            # The save's allgather is collective; the barrier keeps
            # followers from racing ahead (a rebuild's restore must
            # not read a checkpoint the leader is still writing).
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"dopt-serve-ckpt-{t}")
        self._observe_latency(
            "checkpoint_save",
            time.perf_counter() - t0, t)  # dopt: allow-wallclock -- checkpoint_save SLO latency meter, reporting only
        if self.is_leader and self.monitor is not None:
            from dopt.utils.metrics import atomic_write_text

            atomic_write_text(self.state_dir / _MONITOR_FILE,
                              json.dumps(self.monitor.state()))
        self._last_ckpt = t

    def _write_status(self, round_: int | None = None) -> None:
        if not self.is_leader:
            return
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(self.state_dir / _STATUS_FILE, json.dumps({
            "pid": os.getpid(),
            "round": int(round_ if round_ is not None
                         else getattr(self.trainer, "round", 0)),
            "status": self.status,
            "paused": self.paused,
            "checkpoint_every": self.checkpoint_every,
            "restarts": self.restarts,
            "admin_port": self.admin.port if self.admin else None,
            "num_processes": self.num_processes,
            "metrics": str(self.metrics_path),
        }, indent=2))

    # -- liveness heartbeats & decoupled membership --------------------
    def _liveness_path(self, rank: int) -> Path:
        return self.fleet_dir / f"{_LIVENESS_PREFIX}{int(rank)}.json"

    def _write_liveness(self, round_: int) -> None:
        """Refresh this process's heartbeat file.  Operational state
        only (like ``serve.json``): never a telemetry event, never
        replay data — a lost heartbeat costs at worst one spurious
        peer-side leave/join cycle."""
        from dopt.utils.metrics import atomic_write_text

        try:
            atomic_write_text(self._liveness_path(self._liveness_rank),
                              json.dumps({
                                  "pid": os.getpid(),
                                  "rank": self._liveness_rank,
                                  "round": int(round_),
                                  "status": self.status,
                                  "ts": time.time(),  # dopt: allow-wallclock -- liveness heartbeat stamp, operational file only
                              }))
        except OSError:
            pass   # a missed heartbeat is survivable; a crash here is not

    @staticmethod
    def lanes_of(rank: int, fleet_size: int, num_workers: int) -> range:
        """The lane range decoupled process ``rank`` is authoritative
        for: the same even W//N split the SPMD mesh shards."""
        rank, n = int(rank), int(fleet_size)
        w = int(num_workers)
        return range(rank * w // n, (rank + 1) * w // n)

    def _peer_state(self, rank: int) -> str:
        """'live', 'gone', or 'unknown' (never started / torn write —
        no transition either way) from the peer's heartbeat file."""
        try:
            info = json.loads(self._liveness_path(rank).read_text())
        except (OSError, ValueError):
            return "unknown"
        if str(info.get("status")) in ("draining", "drained",
                                       "restarting"):
            return "gone"   # explicit departure stamp: no timeout wait
        age = time.time() - float(info.get("ts", 0.0))  # dopt: allow-wallclock -- peer staleness vs heartbeat stamp, liveness only
        return "gone" if age > self.peer_timeout_s else "live"

    def _peer_transitions(self, t: int) -> list[dict[str, Any]]:
        """Decoupled fleets: fold peer liveness into auto membership
        commands for THIS boundary.  A gone peer's lanes leave (the
        churn repair turns their mixing rows to identity, so the round
        proceeds without them); a returned peer's lanes join back.
        Wall-clock-scheduled by construction — the rows are ledgered
        ``auto`` like the drop_rate auto-pause, and WHICH boundary
        carries them varies run to run (documented in the module
        docstring); everything downstream of the ledger stays
        deterministic."""
        w = int(self.trainer.num_workers)
        away = self.membership.away_at(t, w)
        out: list[dict[str, Any]] = []
        for rank in range(self.fleet_size):
            if rank == self.fleet_rank:
                continue
            state = self._peer_state(rank)
            if state == "unknown":
                continue
            for i in self.lanes_of(rank, self.fleet_size, w):
                if state == "gone" and not away[i]:
                    out.append(make_command(
                        "membership", worker=int(i), action="leave",
                        id=f"auto-liveness-leave-r{t}-w{i}"))
                elif state == "live" and away[i]:
                    out.append(make_command(
                        "membership", worker=int(i), action="join",
                        id=f"auto-liveness-join-r{t}-w{i}"))
        return out

    # -- multi-process directives --------------------------------------
    def _directive_path(self, seq: int, t: int) -> Path:
        # Keyed by (visit sequence, round): a rebuild revisits the same
        # round, and a round-only key would let a follower re-read the
        # stale pre-rebuild directive and double-apply it.
        return self.state_dir / _EPOCH_DIR / f"{seq:06d}-{t}.json"

    def _publish_directive(self, seq: int,
                           directive: dict[str, Any]) -> None:
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(self._directive_path(seq, directive["round"]),
                          json.dumps(directive))

    def _await_directive(self, seq: int, t: int) -> dict[str, Any]:
        # Capped exponential backoff, not a fixed-cadence spin: the
        # first polls catch a prompt leader within ~poll_s, the 1s cap
        # bounds the latency a slow boundary pays, and the total wall
        # budget matches the old poll_s × max_polls product so tuned
        # deployments keep their timeout.
        path = self._directive_path(seq, t)
        budget = self._directive_poll_s * self._directive_max_polls
        deadline = time.monotonic() + budget  # dopt: allow-wallclock -- follower directive-barrier timeout, control plane only
        delay = self._directive_poll_s
        while True:
            if path.exists():
                try:
                    return json.loads(path.read_text())
                except ValueError:
                    pass   # racing the rename: retry
            left = deadline - time.monotonic()  # dopt: allow-wallclock -- follower directive-barrier timeout, control plane only
            if left <= 0:
                break
            time.sleep(min(delay, left))
            delay = min(delay * 2.0, max(self._directive_poll_s, 1.0))
        raise RuntimeError(
            f"process {self.process_id}: no boundary directive for round "
            f"{t} (visit {seq}) after {budget:.0f}s; leader liveness: "
            f"{self._leader_liveness_age()}; last directive published: "
            f"{self._last_directive_seen()}.  A fresh liveness file "
            "means the leader is alive but slow (raise "
            "directive_poll_s/directive_max_polls); a stale or missing "
            "one means the leader is gone (restart the fleet)")

    def _leader_liveness_age(self) -> str:
        """The leader heartbeat's age, rendered for the directive
        timeout — the one bit that tells a dead leader from a slow
        one."""
        p = self._liveness_path(0)
        try:
            info = json.loads(p.read_text())
            age = time.time() - float(info["ts"])  # dopt: allow-wallclock -- timeout diagnostics, reporting only
        except (OSError, ValueError, KeyError, TypeError):
            return f"no heartbeat file at {p}"
        return (f"heartbeat {age:.1f}s old "
                f"(status {info.get('status')!r}, "
                f"round {info.get('round')}, pid {info.get('pid')})")

    def _last_directive_seen(self) -> str:
        """The newest directive seq present in the epoch dir (timeout
        diagnostics: 'leader stopped publishing after seq K')."""
        try:
            names = sorted(p.name for p in
                           (self.state_dir / _EPOCH_DIR).glob("*.json"))
        except OSError:
            names = []
        return names[-1].rsplit(".", 1)[0] if names else "none"

    # -- on-demand live profiling (POST /admin/profile) ----------------
    def request_profile(self, rounds: int) -> dict[str, Any]:
        """Arm a ``jax.profiler`` trace capture for the next ``rounds``
        training rounds (admin thread; takes effect at the next
        boundary).  Observability only: no ledger row, no telemetry
        event, no training-state effect — arming it leaves History,
        fault ledger and canonical stream bit-identical to an
        unprofiled run."""
        rounds = int(rounds)
        if not 1 <= rounds <= 10_000:
            raise ValueError(
                f"profile rounds must be in [1, 10000], got {rounds}")
        with self._profile_lock:
            if self._profile is not None or self._profile_pending:
                raise ValueError(
                    "a profile capture is already armed or active "
                    f"({self.profile_status()})")
            self._profile_pending = rounds
        return self.profile_status()

    def profile_status(self) -> dict[str, Any]:
        prof = self._profile
        return {
            "pending_rounds": self._profile_pending,
            "active": None if prof is None else {
                "start_round": prof["start"], "rounds": prof["rounds"]},
            "artifacts": list(self._profile_artifacts),
        }

    def _profile_tick(self, t: int, verdict: str) -> None:
        """Boundary hook: stop a capture whose window elapsed (or whose
        run is stopping), then start an armed one.  Runs strictly
        outside the round dispatch — the capture wraps whole rounds."""
        prof = self._profile
        if prof is not None and (verdict != "run"
                                 or t >= prof["start"] + prof["rounds"]):
            self._profile_stop(t)
        with self._profile_lock:
            if verdict == "run" and self._profile_pending \
                    and self._profile is None:
                rounds, self._profile_pending = self._profile_pending, 0
                self._profile_start(t, rounds)

    def _profile_start(self, t: int, rounds: int) -> None:
        import jax

        trace_dir = self.state_dir / "profile" / f"r{t}"
        trace_dir.mkdir(parents=True, exist_ok=True)
        try:
            jax.profiler.start_trace(str(trace_dir))
        except Exception as e:   # profiler already active, backend quirk
            print(f"dopt serve: profile capture failed to start: {e}",
                  file=sys.stderr, flush=True)
            return
        self._profile = {"start": t, "rounds": int(rounds),
                         "dir": str(trace_dir)}
        print(f"dopt serve: profiling armed for {rounds} round(s) "
              f"from round {t}", file=sys.stderr, flush=True)

    def _profile_stop(self, t: int) -> None:
        import jax

        prof, self._profile = self._profile, None
        if prof is None:
            return
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            print(f"dopt serve: profile capture failed to stop: {e}",
                  file=sys.stderr, flush=True)
            return
        try:
            artifact = self._write_profile_artifact(prof, t)
        except (OSError, ValueError) as e:
            print(f"dopt serve: profile artifact failed: {e}",
                  file=sys.stderr, flush=True)
            return
        self._profile_artifacts.append(str(artifact))
        print(f"dopt serve: profile artifact {artifact} "
              f"(rounds {prof['start']}..{t})", file=sys.stderr,
              flush=True)

    def _write_profile_artifact(self, prof: dict[str, Any],
                                t: int) -> Path:
        """Merge the XLA trace the profiler dumped with the telemetry
        span tracer's host spans into ONE loadable Chrome trace: device
        events keep their pids, host spans ride a dedicated synthetic
        process track."""
        import gzip

        from dopt.utils.metrics import atomic_write_text

        events: list[dict[str, Any]] = []
        for gz in sorted(Path(prof["dir"]).glob("**/*.trace.json.gz")):
            with gzip.open(gz, "rt") as fh:
                data = json.load(fh)
            events.extend(data.get("traceEvents", []))
        host_pid = 900_000 + self.process_id
        if self.telemetry is not None:
            spans = self.telemetry.tracer.to_chrome()
            if spans:
                events.append({"name": "process_name", "ph": "M",
                               "pid": host_pid,
                               "args": {"name": "dopt host spans"}})
                events.extend({**s, "pid": host_pid} for s in spans)
        out = (self.state_dir / "profile"
               / f"profile-r{prof['start']}-r{t}.trace.json")
        atomic_write_text(out, json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms"}))
        return out

    # -- the serve loop ------------------------------------------------
    def serve(self) -> int:
        """Run until drained (returns 0) or told to restart (returns
        ``EX_RESTART`` — the caller re-execs or the supervisor
        respawns)."""
        while True:
            verdict = self.trainer.run_served(self)
            if verdict == "rebuild":
                self._rebuild()
                continue
            if verdict == "drain":
                self._finalize("drained")
                return 0
            self._finalize("restarting")
            return EX_RESTART

    def _rebuild(self) -> None:
        """Config change took effect: reconstruct the trainer under the
        updated config and restore the boundary checkpoint — the same
        bit-exact save/restore path a kill-and-resume takes, minus the
        process exit."""
        trainer = build_serve_trainer(self.cfg, self.membership)
        if not self.is_leader:
            trainer.checkpoint_writer = False
        t0 = time.perf_counter()  # dopt: allow-wallclock -- checkpoint_restore SLO latency meter, reporting only
        trainer.restore(self.ckpt_path)
        restore_s = time.perf_counter() - t0  # dopt: allow-wallclock -- checkpoint_restore SLO latency meter, reporting only
        if self.telemetry is not None:
            from dopt.obs import attach

            attach(trainer, self.telemetry,
                   checkpoint_every=self.checkpoint_every or None)
        self.trainer = trainer
        self._observe_latency("checkpoint_restore", restore_s,
                              int(trainer.round))

    def _finalize(self, status: str) -> None:
        self.status = status
        if self._profile is not None:
            # A drain/restart landed mid-capture: close the trace and
            # write the (partial) artifact rather than leaking an
            # active profiler session into process exit.
            self._profile_stop(int(getattr(self.trainer, "round", 0)))
        if self.is_leader:
            # Consume any follower stop request on the way out — a
            # stale flag would stop the next serve of this state dir
            # at its first boundary.
            try:
                (self.state_dir / _RESTART_FLAG).unlink(missing_ok=True)
            except OSError:
                pass
            if status == "drained":
                from dopt.utils.metrics import atomic_write_text

                report = (self.monitor.report().to_dict()
                          if self.monitor is not None else None)
                # The SLO latency summary (p50/p95/p99 per name): the
                # monitor's histograms accumulate from the latency
                # events and are checkpointed with its state, so a
                # restarted run's drain still summarizes the whole
                # run's latencies.
                slo = (report or {}).get("latency") or {}
                atomic_write_text(self.state_dir / _FINAL_FILE, json.dumps({
                    "round": int(self.trainer.round),
                    "history": self.trainer.history.rows,
                    "fault_ledger": self.trainer.history.faults,
                    "restarts": self.restarts,
                    "report": report,
                    "slo": slo,
                    "profiles": list(self._profile_artifacts),
                }, indent=2))
        if self.admin is not None:
            self.admin.shutdown()
            self.admin = None
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        self.ledger.close()
        self._write_status()
        # The departure stamp: peers reading "draining"/"restarting"
        # leave this process's lanes WITHOUT waiting out the staleness
        # timeout — the fast half of the decoupled drain protocol.
        self._write_liveness(int(getattr(self.trainer, "round", 0)))

    # -- admin-facing helpers ------------------------------------------
    def submit(self, command: dict[str, Any]) -> dict[str, Any]:
        """Queue one command (validated); applied at a round boundary."""
        return self.queue.submit(command)

    def snapshot(self) -> dict[str, Any]:
        """Status for ``GET /admin/status``."""
        trainer = self.trainer
        return {
            "status": self.status,
            "round": int(getattr(trainer, "round", 0)),
            "paused": self.paused,
            "checkpoint_every": self.checkpoint_every,
            "last_checkpoint_round": self._last_ckpt,
            "restarts": self.restarts,
            "pending_commands": [c.get("id") for c in self._pending],
            "workers": getattr(trainer, "num_workers", None),
            "engine": getattr(trainer, "engine_kind", None),
            "max_rounds": self.max_rounds,
            "num_processes": self.num_processes,
            "fleet_rank": self.fleet_rank,
            "fleet_size": self.fleet_size,
            "profile": self.profile_status(),
        }

    def membership_snapshot(self) -> dict[str, Any]:
        import numpy as np

        trainer = self.trainer
        w = getattr(trainer, "num_workers", 0)
        away = (self.membership.away_at(int(trainer.round), w)
                if self.membership is not None and w
                else np.zeros(0, bool))
        return {"workers": int(w),
                "present": [int(i) for i in np.nonzero(~away)[0]],
                "away": [int(i) for i in np.nonzero(away)[0]],
                "log": self.membership.to_json()
                if self.membership is not None else []}

    def config_snapshot(self) -> dict[str, Any]:
        cfg = self.cfg
        out: dict[str, Any] = {"checkpoint_every": self.checkpoint_every,
                               "paused": self.paused}
        if cfg.optim is not None:
            out["optim.lr"] = cfg.optim.lr
        if cfg.population is not None:
            out["population.cohort"] = cfg.population.cohort
        return out
