"""Unified fault injection & recovery (``FaultPlan``).

The reference has no failure handling at all (SURVEY §5): every
simulated worker is assumed alive and instant.  Production-scale
decentralized training treats crashes, stragglers and partitions as the
steady state ("From promise to practice", arXiv:2410.11998; FusionLLM,
arXiv:2410.12707).  This module is the single source of truth for what
fails when:

* **Crashes** — a worker is down for the round.  Gossip: it skips
  consensus and local training (its mixing row is repaired to identity,
  its lane frozen via ``where_mask``) and rejoins next round with stale
  state.  Federated: it contributes nothing to the server aggregate.
* **Stragglers** — a deadline model: slow workers finish only
  ``straggle_frac`` of their local epochs/steps (the engines gate the
  SGD scan per worker, ``dopt.engine.local``), or — federated with
  ``straggler_policy='drop'`` — are dropped by the server deadline,
  with optional over-selection so the aggregate still averages ~m
  clients (the FedAvg-paper pattern).
* **Partitions** — the fleet splits into random groups for a span of
  rounds.  Gossip: cross-group mixing edges are cut and the matrix
  repaired as data (``dopt.topology.repair_for_partition``).
  Federated: only group 0 can reach the server.
* **Corruption** — the Byzantine model: a worker that LIES rather than
  dies.  Its contributed update (federated) / broadcast state (gossip)
  is replaced by NaN/Inf poison, a norm blow-up, a sign flip, or a
  stale replay (``corrupt_update``, injected inside the jitted round
  functions).  The defense side lives in ``dopt.robust``: non-finite
  screening, robust aggregators, clipped gossip, quarantine.

Every draw is keyed **statelessly** by (seed, kind, round) — no RNG
state is carried between rounds — which is what makes fault traces
(a) identical between per-round and fused-block execution, (b) exactly
replayable from the config alone, and (c) crash-exact under
checkpoint/resume: a run killed at round t and resumed sees precisely
the faults a continuous run would.  Statelessness is also what lets
the engines' fused blocked scans precompute a whole block's fault
inputs up front and stack them as scan inputs ([k, ...] masks, limits,
link-matrix stacks): since PR 4 EVERY fault kind — including the
quarantine/staleness/push-sum modes whose round-to-round state now
rides the scan carry — executes blocked with a bit-identical trace
(docs/ARCHITECTURE.md "Everything is scan carry").

Every injected fault is recorded in the run's **fault ledger**
(``dopt.utils.metrics.History.faults``): one row per (round, worker,
kind, action taken), checkpointed with the rest of the training state.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Iterable

import numpy as np

from dopt.config import FaultConfig
from dopt.utils.prng import host_rng

# Salt namespace for the fault streams (distinct from the engines'
# sampling/matching salts so enabling faults never perturbs them).
_FAULT_SALT = 0xFA010
_CRASH, _STRAGGLE, _PARTITION, _CORRUPT = 1, 2, 3, 4
_LINK, _UPLINK, _CHURN, _STALE = 5, 6, 7, 8

KINDS = ("crash", "straggler", "partition", "overselect", "corrupt",
         "quarantine", "msg_drop", "msg_delay", "churn", "staleness",
         "cohort", "control")
# "control" (dopt.serve): one row per APPLIED control-plane command —
# {round, worker (-1 for fleet-level config/drain/pause rows, the
# worker id for membership rows), kind: "control", action:
# "applied_<cmd>_<details>"} — appended at the round boundary the
# command took effect, BEFORE that round's fault rows, so a served
# run's ledger is a complete replay script: re-running the base config
# plus the ledgered commands at their ledgered rounds reproduces the
# run bit-exactly.
# "cohort" (dopt.population): one row per population-sampled round —
# {round, worker: -1, kind: "cohort", action:
# "sampled_{m}_of_{P}_digest_{crc32}_waves_{K}"} — so which clients a
# round drew is auditable (and replayable via the digest) exactly like
# every injected fault.  FaultPlan itself is population-size agnostic:
# the registry constructs it with num_workers = P so every stateless
# per-round draw (crash/corrupt/churn/uplink/...) is keyed by CLIENT
# id, which is what makes corrupt_max-pinned adversaries persist
# across cohorts instead of being reshuffled with the lane binding.
CORRUPT_MODES = ("nan", "inf", "scale", "signflip", "stale")

# The GossipConfig.dropout alias predates FaultPlan; warn once per
# construction that FaultConfig(crash=p) is the spelling that survives.
# crash=p is the degenerate all-links-down case of the per-edge link
# model (a down worker = every in/out edge dropped + no local work);
# tests/test_faults.py pins that routing equivalence.
_DROPOUT_DEPRECATION = (
    "GossipConfig.dropout is deprecated: set "
    "ExperimentConfig.faults=FaultConfig(crash=p) instead (identical "
    "fault trace; dropout will be REMOVED in release 0.2.0)")


@dataclass(frozen=True)
class RoundFaults:
    """One round's fault state, as plain host arrays.

    ``crashed``/``straggler`` are bool [W]; ``epoch_frac`` is float32
    [W] (1.0 for healthy workers, ``straggle_frac`` for stragglers);
    ``partition`` is an int32 [W] group-id vector, or None when no
    partition is active this round; ``corrupt`` is bool [W] (the
    round's Byzantine liars — None on plans predating the field)."""

    round: int
    crashed: np.ndarray
    straggler: np.ndarray
    epoch_frac: np.ndarray
    partition: np.ndarray | None
    corrupt: np.ndarray | None = None

    @property
    def any_fault(self) -> bool:
        return (bool(self.crashed.any()) or bool(self.straggler.any())
                or self.partition is not None
                or (self.corrupt is not None and bool(self.corrupt.any())))


class MembershipLog:
    """Control-plane membership overlay (``dopt.serve``): an ordered
    log of ``(round, worker, present)`` directives.

    Unlike ``FaultConfig.churn`` — whose leave/join events are random
    draws — these are COMMANDED transitions: the serve daemon appends
    one entry per applied ``membership`` command at the round boundary
    it took effect.  ``away_at(t)`` is a pure function of the log and
    the round index (the last directive with ``round <= t`` wins per
    worker), so membership is stateless-per-round exactly like every
    FaultPlan draw: per-round, blocked, and killed-and-resumed
    execution see the identical fleet, and a resumed daemon rebuilds
    the overlay by replaying its applied-command ledger.

    The log rides the EXISTING churn machinery end to end: a departed
    worker's mixing row is repaired to identity (gossip), it is
    excluded from sampling (federated), its data shards are
    deterministically reassigned to the next-alive adopter
    (``dopt.data.partition.reassign_shards``), and the leave/rejoin/
    shard-adoption transitions land in the fault ledger as ``churn``
    rows."""

    def __init__(self, events: Iterable[tuple[int, int, bool]] = ()):
        self.events: list[tuple[int, int, bool]] = []
        for r, w, p in events:
            self.add(r, w, p)

    def add(self, round_idx: int, worker: int, present: bool) -> None:
        """Append one directive.  Rounds must be nondecreasing — the
        serve daemon applies commands at successive round boundaries,
        and a backdated directive would rewrite already-executed
        rounds' membership."""
        r, w = int(round_idx), int(worker)
        if r < 0 or w < 0:
            raise ValueError(
                f"membership directive needs round >= 0 and worker >= 0 "
                f"(got round={r}, worker={w})")
        if self.events and r < self.events[-1][0]:
            raise ValueError(
                f"membership directives must be appended in round order: "
                f"round {r} after round {self.events[-1][0]}")
        self.events.append((r, w, bool(present)))

    def away_at(self, t: int, num_workers: int) -> np.ndarray:
        """[W] bool: workers commanded away as of round ``t``."""
        away = np.zeros(int(num_workers), bool)
        for r, w, present in self.events:
            if r > int(t):
                break
            if w < num_workers:
                away[w] = not present
        return away

    def to_json(self) -> list[list]:
        return [[int(r), int(w), bool(p)] for r, w, p in self.events]

    @classmethod
    def from_json(cls, obj: Iterable) -> "MembershipLog":
        return cls((int(r), int(w), bool(p)) for r, w, p in obj)

    def __len__(self) -> int:
        return len(self.events)


class FaultPlan:
    """Deterministic per-round fault-trace generator for one fleet.

    ``cfg=None`` (with ``dropout=0``) is the explicit fault-free plan:
    ``for_round`` returns all-alive states and the engines compile the
    exact pre-fault program.  ``dropout`` is the back-compat alias for
    ``GossipConfig.dropout`` — it synthesizes ``FaultConfig(crash=p)``.

    ``membership`` (``dopt.serve``) arms the commanded-membership
    overlay: ``away_for_round`` ORs the log's directives into the churn
    ``away`` set, which flips ``has_churn``/``affects_matrix`` on at
    construction so the engines compile the elastic program up front —
    a join/leave command later never retraces.  ``membership=None``
    (every scripted run) leaves every flag and draw untouched.
    """

    def __init__(self, num_workers: int, cfg: FaultConfig | None = None, *,
                 seed: int = 0, dropout: float = 0.0,
                 membership: MembershipLog | None = None):
        if cfg is not None and dropout > 0.0:
            raise ValueError(
                "set faults via FaultConfig OR the legacy "
                "GossipConfig.dropout alias, not both")
        if cfg is None and dropout > 0.0:
            import warnings

            warnings.warn(_DROPOUT_DEPRECATION, DeprecationWarning,
                          stacklevel=2)
            cfg = FaultConfig(crash=float(dropout))
        if cfg is not None:
            validate_fault_config(cfg)
        self.cfg = cfg
        self.num_workers = int(num_workers)
        self.seed = (int(cfg.seed) if cfg is not None and cfg.seed is not None
                     else int(seed))
        self.membership = membership
        if membership is not None and self.cfg is None:
            # Arming the overlay makes the plan ACTIVE (departed lanes
            # must freeze via the fault machinery); an all-zero config
            # keeps every stochastic draw off — for_round gates each
            # kind on its probability, so no RNG stream is consumed.
            self.cfg = FaultConfig()

    # -- capability flags (engines key compiled-program shape on these,
    # -- so the fault-free path stays bit-identical to the pre-fault one)
    @property
    def active(self) -> bool:
        if self.membership is not None:
            return True
        c = self.cfg
        return c is not None and (c.crash > 0 or c.straggle > 0
                                  or c.partition > 0 or c.corrupt > 0
                                  or c.msg_drop > 0 or c.msg_delay > 0
                                  or c.churn > 0)

    @property
    def may_straggle(self) -> bool:
        return self.active and self.cfg.straggle > 0

    @property
    def has_corrupt(self) -> bool:
        """Byzantine corruption possible (keys the engines' compiled
        corrupt-injection inputs, like may_straggle keys the limits)."""
        return self.active and self.cfg.corrupt > 0

    @property
    def has_membership(self) -> bool:
        """Commanded-membership overlay armed (dopt.serve): leave/join
        directives may repair the matrix / exclude workers at any round
        boundary, so the elastic machinery compiles in up front."""
        return self.membership is not None

    @property
    def affects_matrix(self) -> bool:
        """Crash, partition or churn repair can add identity rows to the
        mixing matrix (the shift path must compile shift 0 into its
        set)."""
        return self.has_membership or (
            self.active and (self.cfg.crash > 0 or self.cfg.partition > 0
                             or self.cfg.churn > 0))

    @property
    def has_link(self) -> bool:
        """Per-edge link faults possible (msg_drop / msg_delay): the
        gossip engine then routes through the link-matrix consensus path
        (dense, per-round) and the federated engine draws uplink
        faults."""
        return self.active and (self.cfg.msg_drop > 0
                                or self.cfg.msg_delay > 0)

    @property
    def has_churn(self) -> bool:
        """Elastic-membership leave/join events possible — random
        (``FaultConfig.churn`` draws) or commanded (the dopt.serve
        ``MembershipLog`` overlay); both ride the same away/repair/
        shard-reassignment machinery."""
        return self.has_membership or (self.active and self.cfg.churn > 0)

    @property
    def delay_max(self) -> int:
        """Compiled staleness-buffer depth D: msg_delay_max when delays
        are possible, else 0 (no buffer)."""
        return (int(self.cfg.msg_delay_max)
                if self.active and self.cfg.msg_delay > 0 else 0)

    # ------------------------------------------------------------------
    def _rng(self, kind: int, t: int) -> np.random.Generator:
        return host_rng(self.seed, _FAULT_SALT, kind, int(t))

    def for_round(self, t: int) -> RoundFaults:
        w = self.num_workers
        none = np.zeros(w, bool)
        if not self.active:
            return RoundFaults(int(t), none, none, np.ones(w, np.float32),
                               None, none)
        c = self.cfg
        crashed = (self._rng(_CRASH, t).random(w) < c.crash
                   if c.crash > 0 else none)
        straggler = (self._rng(_STRAGGLE, t).random(w) < c.straggle
                     if c.straggle > 0 else none)
        straggler = straggler & ~crashed   # a crashed worker cannot straggle
        frac = np.where(straggler, np.float32(c.straggle_frac),
                        np.float32(1.0)).astype(np.float32)
        corrupt = none
        if c.corrupt > 0:
            corrupt = self._rng(_CORRUPT, t).random(w) < c.corrupt
            corrupt &= ~crashed   # a down worker sends nothing to corrupt
            if c.corrupt_max > 0 and int(corrupt.sum()) > c.corrupt_max:
                # Cap keeps the LOWEST-INDEXED liars, so corrupt=1.0 +
                # corrupt_max=f pins workers 0..f-1 as the persistent
                # adversary set (the fixed-f Byzantine setting).
                keep = np.nonzero(corrupt)[0][:c.corrupt_max]
                corrupt = np.zeros(w, bool)
                corrupt[keep] = True
        return RoundFaults(int(t), crashed, straggler, frac,
                           self._partition_for_round(t), corrupt)

    def _partition_for_round(self, t: int) -> np.ndarray | None:
        """Partition active at t ⇔ one started at some s ∈ (t−span, t];
        the most recent start wins.  Start draws and group assignments
        are keyed by the START round, so a partition's membership is
        stable over its whole span."""
        c = self.cfg
        if c is None or c.partition <= 0:
            return None
        for s in range(int(t), max(int(t) - c.partition_span, -1), -1):
            r = self._rng(_PARTITION, s)
            if r.random() < c.partition:
                groups = r.integers(0, c.partition_groups,
                                    size=self.num_workers)
                return groups.astype(np.int32)
        return None

    # -- link faults (per-(round, directed edge) stateless draws) ------
    def link_for_round(self, t: int) -> tuple[np.ndarray, np.ndarray]:
        """(keep, delay) for round t's directed edges.

        ``keep`` is bool [W, W]: keep[i, j] = the message j -> i
        survives this round (diagonal always True — a worker never
        drops its own state).  ``delay`` is int32 [W, W]: rounds of
        staleness on edge j -> i, in {0..msg_delay_max} (0 on the
        diagonal and on dropped edges — a dropped message never
        arrives, late or otherwise).  Both directions of a link draw
        independently, so loss/delay is asymmetric in general.  Draws
        are keyed by (seed, _LINK, round) only — bit-reproducible,
        blocked-exact and resume-exact like every other fault kind."""
        w = self.num_workers
        eye = np.eye(w, dtype=bool)
        if not self.has_link:
            return np.ones((w, w), bool), np.zeros((w, w), np.int32)
        c = self.cfg
        r = self._rng(_LINK, t)
        # One fixed draw layout regardless of which knobs are on, so
        # enabling msg_delay never perturbs the msg_drop trace.
        u_drop = r.random((w, w))
        u_del = r.random((w, w))
        d_val = r.integers(1, max(c.msg_delay_max, 1) + 1, size=(w, w))
        keep = ~((u_drop < c.msg_drop) & ~eye)
        delayed = (u_del < c.msg_delay) & ~eye & keep
        delay = np.where(delayed, d_val, 0).astype(np.int32)
        return keep, delay

    def uplink_for_round(self, t: int) -> tuple[np.ndarray, np.ndarray]:
        """Federated worker -> server link faults for round t:
        (dropped, delay) as [W] bool / int32 arrays.  ``dropped[i]``
        loses worker i's update for the round; ``delay[i]`` > 0 means
        the update arrives that many rounds late (admitted via the
        staleness buffer when ``FederatedConfig.staleness_max`` allows,
        dropped otherwise).  Drops win ties.  Separate salt from the
        gossip edge draws so the two engines' traces are independent."""
        w = self.num_workers
        if not self.has_link:
            return np.zeros(w, bool), np.zeros(w, np.int32)
        c = self.cfg
        r = self._rng(_UPLINK, t)
        u_drop = r.random(w)
        u_del = r.random(w)
        d_val = r.integers(1, max(c.msg_delay_max, 1) + 1, size=w)
        dropped = u_drop < c.msg_drop
        delayed = (u_del < c.msg_delay) & ~dropped
        return dropped, np.where(delayed, d_val, 0).astype(np.int32)

    def straggler_lateness(self, t: int, max_late: int) -> np.ndarray:
        """[W] int32 lateness draws in 1..max_late: how many rounds
        after its deadline a buffered straggler's update arrives.  The
        bound is the CALLER's admission window (federated
        ``staleness_max``), not ``msg_delay_max`` — straggler lateness
        is an aggregation-policy property, independent of whether the
        message-delay fault is configured.  Keyed (seed, _STALE, round)
        — stateless."""
        w = self.num_workers
        hi = max(int(max_late), 1)
        return self._rng(_STALE, t).integers(1, hi + 1,
                                             size=w).astype(np.int32)

    # -- churn (elastic membership) ------------------------------------
    def away_for_round(self, t: int) -> np.ndarray:
        """[W] bool: workers away (departed) at round t.  Worker i is
        away at t iff a leave event keyed at some round s in
        (t - churn_span, t] fired for it — the same span-scan scheme as
        partitions, so membership is a pure function of the round index
        (stateless, resume-exact) and every leave lasts exactly
        ``churn_span`` rounds before the rejoin."""
        w = self.num_workers
        away = np.zeros(w, bool)
        if self.membership is not None:
            away |= self.membership.away_at(t, w)
        if not (self.active and self.cfg.churn > 0):
            return away
        c = self.cfg
        for s in range(int(t), max(int(t) - c.churn_span, -1), -1):
            away |= self._rng(_CHURN, s).random(w) < c.churn
        return away

    def plan_matrix_for(self, t: int,
                        train_matrix: np.ndarray) -> np.ndarray:
        """Round t's batch-plan index matrix: ``train_matrix`` with
        departed workers' shards deterministically reassigned to their
        adopters while churn keeps them away (the engines' shared
        shard-reassignment hook; a no-op without churn)."""
        if not self.has_churn:
            return train_matrix
        from dopt.data.partition import reassign_shards

        away = self.away_for_round(t)
        return reassign_shards(train_matrix, self.adopters_for(away))

    @staticmethod
    def adopters_for(away: np.ndarray) -> dict[int, int]:
        """Deterministic shard-reassignment map for a round's departed
        set: each away worker i is adopted by the first alive worker at
        (i+1, i+2, ...) mod W.  Empty when everyone (or no one) is
        away."""
        w = len(away)
        if not away.any() or away.all():
            return {}
        out: dict[int, int] = {}
        for i in np.nonzero(away)[0]:
            j = (int(i) + 1) % w
            while away[j]:
                j = (j + 1) % w
            out[int(i)] = j
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def limits_for(rf: RoundFaults, total_units: int) -> np.ndarray:
        """Per-worker work limits in the engine's granularity (epochs
        under the holdout's epoch loop, SGD steps on the flat path):
        healthy workers get ``total_units``, stragglers
        ``ceil(frac · total_units)`` (≥ 1 for frac > 0)."""
        lim = np.ceil(rf.epoch_frac * float(total_units))
        return np.clip(lim, 0, total_units).astype(np.int32)


def churn_ledger_rows(plan: FaultPlan, t: int,
                      away: np.ndarray) -> list[dict]:
    """Elastic-membership ledger rows for round t: leave/rejoin
    transitions and shard-adoption changes, recomputed statelessly from
    the round index alone (so per-round, blocked and killed-and-resumed
    execution log the identical trace).  Shared by both engines."""
    rows: list[dict] = []
    prev = (plan.away_for_round(t - 1) if t > 0
            else np.zeros_like(away))
    for i in np.nonzero(away & ~prev)[0]:
        rows.append({"round": int(t), "worker": int(i), "kind": "churn",
                     "action": "left"})
    for i in np.nonzero(prev & ~away)[0]:
        rows.append({"round": int(t), "worker": int(i), "kind": "churn",
                     "action": "rejoined"})
    adopters = plan.adopters_for(away)
    prev_adopters = plan.adopters_for(prev)
    for i, a in sorted(adopters.items()):
        if prev_adopters.get(i) != a:
            rows.append({"round": int(t), "worker": int(i), "kind": "churn",
                         "action": f"shard_adopted_by_{a}"})
    return rows


def validate_fault_config(cfg: FaultConfig) -> None:
    """Range/enum checks shared by ``FaultPlan`` and the CLI parser (so
    a bad ``--faults`` value fails at parse time with a clean message,
    not as a traceback from trainer construction)."""
    for f in ("crash", "straggle", "partition"):
        v = getattr(cfg, f)
        if not 0.0 <= v <= 1.0:
            raise ValueError(f"FaultConfig.{f}={v} must be in [0, 1]")
    if not 0.0 <= cfg.straggle_frac <= 1.0:
        raise ValueError(
            f"FaultConfig.straggle_frac={cfg.straggle_frac} must be "
            "in [0, 1]")
    if cfg.straggle > 0 and cfg.straggle_frac <= 0.0:
        # A zero-step straggler would leave p_t == theta, which corrupts
        # SCAFFOLD's control refresh (c_i drifts by -c_global every time
        # the worker is sampled).  Zero work for the round IS a crash —
        # model it with `crash` instead.
        raise ValueError(
            "FaultConfig.straggle_frac must be > 0 when straggle > 0 "
            "(a straggler always finishes SOME work; use crash for "
            "workers that do none)")
    if cfg.straggler_policy not in ("partial", "drop"):
        raise ValueError(
            f"unknown straggler_policy {cfg.straggler_policy!r}; "
            "one of partial|drop")
    if cfg.over_select < 0.0:
        raise ValueError("FaultConfig.over_select must be >= 0")
    if cfg.partition_span < 1:
        raise ValueError("FaultConfig.partition_span must be >= 1")
    if cfg.partition_groups < 2:
        raise ValueError("FaultConfig.partition_groups must be >= 2")
    if not 0.0 <= cfg.corrupt <= 1.0:
        raise ValueError(
            f"FaultConfig.corrupt={cfg.corrupt} must be in [0, 1]")
    if cfg.corrupt_mode not in CORRUPT_MODES:
        raise ValueError(
            f"unknown corrupt_mode {cfg.corrupt_mode!r}; one of "
            f"{CORRUPT_MODES}")
    if not np.isfinite(cfg.corrupt_scale) or cfg.corrupt_scale == 0.0:
        raise ValueError(
            f"FaultConfig.corrupt_scale={cfg.corrupt_scale} must be a "
            "finite nonzero factor (use corrupt_mode='inf' for "
            "non-finite poison)")
    if cfg.corrupt_max < 0:
        raise ValueError("FaultConfig.corrupt_max must be >= 0")
    for f in ("msg_drop", "msg_delay", "churn"):
        v = getattr(cfg, f)
        if not 0.0 <= v <= 1.0:
            raise ValueError(f"FaultConfig.{f}={v} must be in [0, 1]")
    if cfg.msg_drop == 1.0:
        # msg_drop=1.0 cuts EVERY off-diagonal edge every round — no
        # message ever arrives, which is 'nocons', not a lossy link.
        raise ValueError(
            "FaultConfig.msg_drop must be < 1 (dropping every message "
            "every round leaves no communication to degrade; use "
            "algorithm='nocons' for no-communication runs)")
    if cfg.msg_delay_max < 1:
        raise ValueError("FaultConfig.msg_delay_max must be >= 1")
    if cfg.churn_span < 1:
        raise ValueError("FaultConfig.churn_span must be >= 1")


def parse_fault_spec(spec: str) -> FaultConfig:
    """CLI ``--faults`` spec → FaultConfig.

    e.g. ``--faults "crash=0.1,straggle=0.2,straggle_frac=0.5,partition=0.05"``
    — keys are FaultConfig field names, values coerced to the field's
    annotated type, unknown keys rejected loudly."""
    fields = {f.name: f for f in dataclasses.fields(FaultConfig)}
    kw: dict[str, object] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, raw = part.partition("=")
        key = key.strip()
        if not eq or key not in fields:
            raise ValueError(
                f"--faults: unknown field {key!r}; one of {sorted(fields)}")
        ann = str(fields[key].type)
        try:
            if ann.startswith("int"):
                kw[key] = int(raw)
            elif ann.startswith("float"):
                kw[key] = float(raw)
            else:
                kw[key] = raw.strip()
        except ValueError:
            raise ValueError(
                f"--faults: field {key!r} expects {ann}, got {raw!r}")
    cfg = FaultConfig(**kw)
    validate_fault_config(cfg)
    return cfg


# CLI --corrupt shorthand: short keys -> FaultConfig field names.
_CORRUPT_KEYS = {"p": "corrupt", "mode": "corrupt_mode",
                 "scale": "corrupt_scale", "max": "corrupt_max"}


def parse_corrupt_spec(spec: str, base: FaultConfig | None = None) -> FaultConfig:
    """CLI ``--corrupt`` spec, merged onto an existing FaultConfig.

    e.g. ``--corrupt "p=0.25,mode=signflip,scale=50,max=2"`` or the bare
    probability ``--corrupt 0.25``.  Keys map onto the FaultConfig
    corrupt_* fields, so crash/straggler faults from ``--faults``
    compose with the Byzantine knobs."""
    kw: dict[str, object] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, raw = part.partition("=")
        if not eq:
            try:
                kw["corrupt"] = float(part)
                continue
            except ValueError:
                raise ValueError(
                    f"--corrupt: expected a probability or key=value, "
                    f"got {part!r}")
        key = key.strip()
        if key not in _CORRUPT_KEYS:
            raise ValueError(
                f"--corrupt: unknown field {key!r}; one of "
                f"{sorted(_CORRUPT_KEYS)}")
        field = _CORRUPT_KEYS[key]
        try:
            if field == "corrupt_mode":
                kw[field] = raw.strip()
            elif field == "corrupt_max":
                kw[field] = int(raw)
            else:
                kw[field] = float(raw)
        except ValueError:
            raise ValueError(f"--corrupt: bad value {raw!r} for {key!r}")
    if "corrupt" not in kw and (base is None or base.corrupt == 0.0):
        kw.setdefault("corrupt", 1.0)   # --corrupt "mode=nan" means "lie"
    cfg = dataclasses.replace(base or FaultConfig(), **kw)
    validate_fault_config(cfg)
    return cfg


def corrupt_update(update, cmask, mode: str, scale: float,
                   ref=None, prev=None):
    """Inject the round's Byzantine corruption into a stacked update —
    jittable, so corrupted runs stay bit-reproducible and blocked /
    compact / resumed execution injects identically.

    ``update`` is the [lanes, ...] stacked pytree a worker contributes
    (post-local-training params in the federated engine, the broadcast
    state in gossip); ``cmask`` the [lanes] 0/1 corrupt mask (data — the
    fault-free mask compiles to a no-op select).  ``ref`` is the
    reference point updates are measured from (theta in the federated
    engine; None = the origin, the gossip case), ``prev`` the previous
    update for mode='stale' (the carried lane state).

    Modes: 'nan'/'inf' poison the lanes outright; 'scale' blows the
    update up by ``scale`` around ``ref``; 'signflip' reflects it
    through ``ref``; 'stale' replays ``prev``.
    """
    import jax
    import jax.numpy as jnp

    from dopt.parallel.collectives import where_mask

    if mode == "nan":
        bad = jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), update)
    elif mode == "inf":
        bad = jax.tree.map(lambda x: jnp.full_like(x, jnp.inf), update)
    elif mode == "scale":
        if ref is None:
            bad = jax.tree.map(lambda x: (x * jnp.asarray(scale, x.dtype)),
                               update)
        else:
            bad = jax.tree.map(
                lambda x, r: r + jnp.asarray(scale, x.dtype) * (x - r),
                update, ref)
    elif mode == "signflip":
        if ref is None:
            bad = jax.tree.map(lambda x: -x, update)
        else:
            bad = jax.tree.map(lambda x, r: (2 * r - x).astype(x.dtype),
                               update, ref)
    elif mode == "stale":
        if prev is None:
            raise ValueError("corrupt_mode='stale' needs the previous "
                             "update (prev=...)")
        bad = prev
    else:
        raise ValueError(f"unknown corrupt_mode {mode!r}; one of "
                         f"{CORRUPT_MODES}")
    return where_mask(cmask, bad, update)
