"""Scrape endpoint over a live telemetry stream: ``python -m dopt.obs.serve``.

Promotes the ``PrometheusSink`` text snapshot into a real HTTP scrape
surface for long soak runs: a stdlib ``http.server`` that tails a
growing metrics JSONL file (byte-offset watermark — each request
processes only the bytes appended since the last one) and serves

* ``GET /metrics``  — Prometheus text exposition: latest round
  metrics and gauges (``engine_kind``-labelled), fault counters, and
  ``dopt_alerts_total`` from the attached ``HealthMonitor``;
* ``GET /healthz``  — the monitor's live ``HealthReport`` verdict as
  JSON; HTTP 200 while the verdict is healthy/warn/empty, 503 once a
  critical rule fired (the shape load balancers and soak harnesses
  poll).

Stdlib-only (no jax): point it at a metrics file scp'd off a TPU pod
or written live by a local run::

    python -m dopt.run --preset baseline1 --rounds 1000 \
        --metrics-out metrics.jsonl &
    python -m dopt.obs.serve metrics.jsonl --port 8000
    curl localhost:8000/metrics
    curl localhost:8000/healthz
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any

from dopt.obs.monitor import HealthMonitor, JsonlTail
from dopt.obs.rules import Rule
from dopt.obs.sinks import PrometheusSink

# Backoff hint every dopt HTTP surface sends on 503: the endpoint is
# critical or still attaching, not gone — poll again, don't hammer.
RETRY_AFTER_S = 5


def http_reply(handler: BaseHTTPRequestHandler, code: int, body: bytes,
               ctype: str, *, retry_after_s: int = RETRY_AFTER_S) -> None:
    """The ONE reply path of every dopt scrape/admin handler
    (dopt.obs.serve, dopt.obs.aggregate, dopt.serve.admin): status,
    Content-Type/-Length, and the ``Retry-After`` header on every 503
    — a header tweak lands on all three surfaces at once."""
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    if code == 503:
        handler.send_header("Retry-After", str(retry_after_s))
    handler.end_headers()
    handler.wfile.write(body)


class MetricsServer:
    """Tail a metrics JSONL file and serve /metrics + /healthz.

    ``port=0`` binds an ephemeral port (read it back from ``.port``
    after ``start()``) — the smoke-test mode.  Each request refreshes
    the tail under a lock, so concurrent scrapes see a consistent
    snapshot and the file is read incrementally, never re-parsed."""

    def __init__(self, metrics_path: str | Path, *,
                 host: str = "127.0.0.1", port: int = 0,
                 rules: list[Rule] | None = None,
                 workers: int | None = None):
        self.metrics_path = Path(metrics_path)
        self.monitor = HealthMonitor(rules, workers=workers)
        self.prom = PrometheusSink()
        self._tail = JsonlTail(self.metrics_path)
        self._lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def refresh(self) -> None:
        """Process the bytes appended since the previous refresh."""
        with self._lock:
            for ev in self._tail.poll():
                self.prom.emit(ev)
                for alert in self.monitor.observe(ev):
                    self.prom.emit(alert)

    def render_metrics(self) -> str:
        self.refresh()
        return self.prom.render()

    def render_health(self) -> tuple[int, str]:
        self.refresh()
        report = self.monitor.report()
        body = report.to_dict()
        body["metrics_path"] = str(self.metrics_path)
        # The monitor's own staleness: wall seconds since the newest
        # event in the stream.  A healthy-but-idle producer and a
        # stalled one report the same verdict; the lag tells them
        # apart (null before the first event).
        body["last_event_ts"] = self.monitor.last_event_ts
        body["lag_seconds"] = self.monitor.lag_seconds()
        return (200 if report.ok else 503), json.dumps(body, indent=2)

    def _handler(self) -> type[BaseHTTPRequestHandler]:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    body = server.render_metrics().encode()
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    code, text = server.render_health()
                    self._reply(code, text.encode(), "application/json")
                elif path == "/":
                    self._reply(200, b"dopt.obs.serve: /metrics /healthz\n",
                                "text/plain")
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                http_reply(self, code, body, ctype)

            def log_message(self, fmt: str, *args: Any) -> None:
                pass  # scrapes every few seconds would flood stderr

        return Handler

    def start(self) -> "MetricsServer":
        """Serve in a daemon thread (the smoke-test / embedded mode)."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("metrics", metavar="METRICS_JSONL",
                    help="telemetry stream to tail (may not exist yet — "
                         "the tail waits for it)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="bind port; 0 binds an EPHEMERAL port — the "
                         "chosen one is announced on stdout (one JSON "
                         "line) and in --state-file, so soak harnesses "
                         "and embedding daemons never fixed-port race")
    ap.add_argument("--workers", type=int, default=None,
                    help="fleet-size denominator override for rules "
                         "(normally recovered from the stream's run "
                         "header)")
    ap.add_argument("--state-file", default=None, metavar="PATH",
                    help="write {host, port, pid, metrics} here "
                         "(atomically) once bound; removed on clean "
                         "shutdown")
    args = ap.parse_args(argv)

    server = MetricsServer(args.metrics, host=args.host, port=args.port,
                           workers=args.workers)
    # The bound port goes to STDOUT as one JSON line (stderr keeps the
    # human banner): `PORT=$(... | head -1 | jq .port)` just works,
    # including under --port 0.
    print(json.dumps({"host": args.host, "port": server.port,
                      "metrics": str(args.metrics), "pid": os.getpid()}),
          flush=True)
    if args.state_file:
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(args.state_file, json.dumps(
            {"host": args.host, "port": server.port, "pid": os.getpid(),
             "metrics": str(args.metrics)}, indent=2))
    print(f"serving {args.metrics} on http://{args.host}:{server.port} "
          f"(/metrics, /healthz)", file=sys.stderr)

    def _term(signum, frame):
        # Graceful SIGTERM: unwind through the KeyboardInterrupt path
        # so the finally block closes the socket and removes the state
        # file — embedding daemons and soak harnesses can stop the
        # endpoint without leaking the port.
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        if args.state_file:
            try:
                os.unlink(args.state_file)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
