"""dopt — a TPU-native distributed-optimization and federated-learning framework.

``dopt`` re-creates the full capability surface of the reference project
"Distributed-Optimization-and-Learning" (two PyTorch single-process
simulations of federated and gossip learning) as a real distributed
framework designed for TPUs:

* Workers are *devices* (or vmapped lanes folded onto devices) on a
  ``jax.sharding.Mesh`` rather than sequentially-stepped Python objects.
* Model/optimizer/dual state for all N workers lives in one *stacked
  pytree* (leading worker axis) sharded across the mesh.
* Gossip consensus (weighted neighbor averaging with a mixing matrix) is
  an XLA collective: ``lax.ppermute`` chains for banded topologies,
  ``all_gather`` + einsum for dense/arbitrary graphs.
* Federated aggregation (FedAvg / FedProx / FedADMM) is a masked
  ``lax.psum`` over the worker axis with client-sampling masks.
* A faithful torch-CPU oracle backend reproduces the reference's exact
  numerics (including its quirks, e.g. the double-softmax head) so the
  TPU path can be validated step-by-step.

Reference layer map: see SURVEY.md §1 in the repository root.
"""

from dopt.config import (
    CommConfig,
    DataConfig,
    ExperimentConfig,
    FaultConfig,
    FederatedConfig,
    GossipConfig,
    ModelConfig,
    OptimizerConfig,
    RobustConfig,
    SeqLMConfig,
    from_reference_args,
)
from dopt.topology import MixingMatrices, Topology, build_mixing_matrices

__version__ = "0.1.0"

# Heavy entry points resolve lazily (PEP 562) so `import dopt` stays
# cheap: the engines pull in flax/model code only when actually used.
_LAZY = {
    "GossipTrainer": ("dopt.engine", "GossipTrainer"),
    "FederatedTrainer": ("dopt.engine", "FederatedTrainer"),
    "SeqLMTrainer": ("dopt.engine", "SeqLMTrainer"),
    "build_model": ("dopt.models", "build_model"),
    "get_preset": ("dopt.presets", "get_preset"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'dopt' has no attribute {name!r}")


def __dir__():
    return sorted(__all__)


__all__ = [
    "from_reference_args",
    "CommConfig",
    "DataConfig",
    "ExperimentConfig",
    "FaultConfig",
    "RobustConfig",
    "FederatedConfig",
    "GossipConfig",
    "ModelConfig",
    "OptimizerConfig",
    "SeqLMConfig",
    "MixingMatrices",
    "Topology",
    "build_mixing_matrices",
    *_LAZY,
]
