"""dopt — a TPU-native distributed-optimization and federated-learning framework.

``dopt`` re-creates the full capability surface of the reference project
"Distributed-Optimization-and-Learning" (two PyTorch single-process
simulations of federated and gossip learning) as a real distributed
framework designed for TPUs:

* Workers are *devices* (or vmapped lanes folded onto devices) on a
  ``jax.sharding.Mesh`` rather than sequentially-stepped Python objects.
* Model/optimizer/dual state for all N workers lives in one *stacked
  pytree* (leading worker axis) sharded across the mesh.
* Gossip consensus (weighted neighbor averaging with a mixing matrix) is
  an XLA collective: ``lax.ppermute`` chains for banded topologies,
  ``all_gather`` + einsum for dense/arbitrary graphs.
* Federated aggregation (FedAvg / FedProx / FedADMM) is a masked
  ``lax.psum`` over the worker axis with client-sampling masks.
* A faithful torch-CPU oracle backend reproduces the reference's exact
  numerics (including its quirks, e.g. the double-softmax head) so the
  TPU path can be validated step-by-step.

Reference layer map: see SURVEY.md §1 in the repository root.
"""

from dopt.config import (
    DataConfig,
    ExperimentConfig,
    FederatedConfig,
    GossipConfig,
    ModelConfig,
    OptimizerConfig,
)
from dopt.topology import MixingMatrices, Topology, build_mixing_matrices

__version__ = "0.1.0"

__all__ = [
    "DataConfig",
    "ExperimentConfig",
    "FederatedConfig",
    "GossipConfig",
    "ModelConfig",
    "OptimizerConfig",
    "MixingMatrices",
    "Topology",
    "build_mixing_matrices",
]
