"""Checkpoint / resume (absent in the reference — SURVEY §5).

The reference persists only metric CSVs; model state lives and dies
with the Colab runtime (the only continuity is ``Server.global_round``
surviving across ``run()`` calls in memory, servers.py:18,78).  dopt
checkpoints the full training state — stacked params, momentum buffers,
ADMM duals, global model, round counter, and metric history — with
orbax for the array pytrees plus a JSON sidecar for scalars/history.

Layout:  <dir>/state/   orbax pytree checkpoint
         <dir>/meta.json  {round, name, history rows}
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def save_checkpoint(path: str | Path, *, arrays: dict[str, Any],
                    meta: dict[str, Any]) -> Path:
    """Save an arrays pytree (orbax) + JSON metadata."""
    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    arrays = {k: _to_numpy(v) for k, v in arrays.items() if v is not None}
    if HAVE_ORBAX:
        ckpt = ocp.PyTreeCheckpointer()
        state_dir = path / "state"
        if state_dir.exists():
            import shutil

            shutil.rmtree(state_dir)
        ckpt.save(state_dir, arrays)
    else:  # numpy fallback keeps the feature alive without orbax
        np.savez(path / "state.npz", **_flatten_for_npz(arrays))
    (path / "meta.json").write_text(json.dumps(meta, indent=2))
    return path


def load_checkpoint(path: str | Path) -> tuple[dict[str, Any], dict[str, Any]]:
    """Returns (arrays, meta)."""
    path = Path(path).absolute()
    meta = json.loads((path / "meta.json").read_text())
    if HAVE_ORBAX and (path / "state").exists():
        ckpt = ocp.PyTreeCheckpointer()
        arrays = ckpt.restore(path / "state")
    else:
        with np.load(path / "state.npz") as z:
            arrays = _unflatten_from_npz(dict(z))
    return arrays, meta


def _flatten_for_npz(tree, prefix="") -> dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten_for_npz(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten_from_npz(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out
