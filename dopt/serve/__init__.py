"""dopt.serve — the resident elastic trainer with a live control plane.

The ROADMAP's "production service mode": one long-lived elastic run
instead of N scripted rounds.  ``python -m dopt.serve --preset
baseline1 --state-dir run/`` owns a training loop until told
otherwise, and everything that happens to it mid-flight — membership
join/leave, whitelisted config changes, checkpoints, admission pauses,
drains — arrives through a versioned command queue, applies at a round
boundary, and is ledgered (fault-ledger ``control`` rows + the
deterministic ``control`` telemetry kind), so a served run stays a
pure function of (base config, applied-command ledger): interruptible,
resumable, and bit-reproducible.

Layers (one module each):

* ``dopt.serve.control`` — command schema, append-only JSONL queue,
  applied-command ledger (the replay source), config whitelist;
* ``dopt.serve.daemon``  — ``ServeDaemon``: the round-boundary
  controller behind the engines' ``run_served`` entry, streaming
  checkpoints, the in-process ``HealthMonitor`` (alerts feed back:
  drop_rate-critical auto-pauses admission), SIGTERM → drain →
  checkpoint → re-exec → bit-exact resume, and the leader/follower
  directive barrier for multi-process fleets;
* ``dopt.serve.admin``   — the stdlib HTTP surface: ``/admin/*``
  command endpoints plus the in-process ``/metrics`` + ``/healthz``;
* ``dopt.serve.__main__`` — the CLI: single-process daemon,
  self-re-exec on SIGTERM, and the multi-process supervisor that grows
  ``scripts/multiprocess_demo.py`` into the supported
  ``jax.distributed`` path.
"""

from __future__ import annotations

from dopt.serve.control import (COMMANDS, CONFIG_WHITELIST, CommandQueue,
                                ControlLedger, make_command,
                                validate_command)
from dopt.serve.daemon import (EX_RESTART, ServeDaemon, build_serve_trainer,
                               serve_rules)

__all__ = [
    "COMMANDS", "CONFIG_WHITELIST", "CommandQueue", "ControlLedger",
    "EX_RESTART", "ServeDaemon", "build_serve_trainer", "make_command",
    "serve_rules", "validate_command",
]
