"""Sequence parallelism: ring attention and Ulysses all-to-all vs the
single-device dense reference, elementwise, on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.parallel.sequence import (dense_attention, make_seq_mesh,
                                    ring_attention, ulysses_attention)


def _qkv(b=2, l=32, h=4, d=8, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    shape = (b, l, h, d)
    return (jax.random.normal(k1, shape, jnp.float32),
            jax.random.normal(k2, shape, jnp.float32),
            jax.random.normal(k3, shape, jnp.float32))


@pytest.fixture(scope="module")
def mesh(devices):
    return make_seq_mesh(8)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(mesh, causal):
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(mesh, causal):
    q, k, v = _qkv(h=8)
    want = dense_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_long_sequence_blocked_memory(mesh):
    # 8 devices x 64-token blocks: a 512-token sequence where no device
    # ever materialises the full [L, L] score matrix.
    q, k, v = _qkv(b=1, l=512, h=2, d=4, seed=3)
    want = dense_attention(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_rejects_indivisible(mesh):
    q, k, v = _qkv(l=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh)


def test_ulysses_rejects_indivisible_heads(mesh):
    q, k, v = _qkv(h=6)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)


def test_transformer_ring_attention_matches_dense(mesh):
    # The zoo transformer with ring attention injected over the 8-device
    # mesh must match its own single-device dense-attention forward.
    from dopt.models import build_model

    model = build_model("transformer", num_classes=64)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 64)
    params = model.init(jax.random.key(0), tokens)["params"]

    dense_out = model.apply({"params": params}, tokens)
    ring = lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
    ring_out = jax.jit(
        lambda p, t: model.apply({"params": p}, t, attn_fn=ring)
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(ring_out), np.asarray(dense_out),
                               atol=3e-5, rtol=3e-5)


def test_ring_attention_gradients_match_dense(mesh):
    # Training parity, not just inference: gradients through the ring
    # (ppermute rotations + lax.scan + flash combine) must match
    # gradients through dense attention.
    q, k, v = _qkv(b=1, l=64, h=2, d=8, seed=7)

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


def test_transformer_seqparallel_training_step(mesh):
    # One full LM training step (CE loss + SGD) with ring attention over
    # the mesh equals the same step computed with dense attention.
    from dopt.models import build_model

    model = build_model("transformer", num_classes=32)
    tokens = jax.random.randint(jax.random.key(2), (2, 64), 0, 32)
    params = model.init(jax.random.key(0), tokens)["params"]
    ring = lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)

    def step(params, attn_fn):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens, attn_fn=attn_fn)
            logp = jax.nn.log_softmax(logits[:, :-1])
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
            return nll.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        return loss, new

    loss_d, new_d = step(params, None)
    loss_r, new_r = jax.jit(lambda p: step(p, ring))(params)
    np.testing.assert_allclose(float(loss_r), float(loss_d), atol=1e-5)
    for a, b in zip(jax.tree.leaves(new_d), jax.tree.leaves(new_r)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------
# SeqLMTrainer: sequence parallelism as a driveable component
# ---------------------------------------------------------------------

def _seqlm_cfg(attn="ring", steps=24, **kw):
    import dataclasses

    from dopt.presets import get_preset

    fields = dict(attn=attn, steps=steps, seq_len=256, batch=4)
    fields.update(kw)
    cfg = get_preset("seqlm")
    return cfg.replace(seqlm=dataclasses.replace(cfg.seqlm, **fields))


@pytest.mark.slow  # ~20s full seqlm run; covered faster by the ulysses twin
def test_seqlm_trainer_loss_drops_on_mesh(devices):
    from dopt.engine import SeqLMTrainer

    tr = SeqLMTrainer(_seqlm_cfg())
    assert tr.mesh.size == 8
    h = tr.run()
    losses = [r["loss"] for r in h.rows]
    # untrained = log(vocab) ≈ 4.16; the Markov floor is log(4) ≈ 1.39
    assert losses[0] > 3.0
    assert losses[-1] < losses[0] - 1.0, losses


def test_seqlm_ulysses_runs_and_learns(devices):
    from dopt.engine import SeqLMTrainer

    tr = SeqLMTrainer(_seqlm_cfg(attn="ulysses", steps=12, heads=8))
    h = tr.run()
    losses = [r["loss"] for r in h.rows]
    assert losses[-1] < losses[0]


@pytest.mark.slow  # ~25s: two full seqlm runs (save + resume)
def test_seqlm_checkpoint_resume(devices, tmp_path):
    import numpy as np
    import jax

    from dopt.engine import SeqLMTrainer

    a = SeqLMTrainer(_seqlm_cfg(steps=8))
    a.run(steps=4)
    a.save(tmp_path / "ck")
    b = SeqLMTrainer(_seqlm_cfg(steps=8))
    b.restore(tmp_path / "ck")
    a.run(steps=4)
    b.run(steps=4)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_seqlm_validation(devices):
    import dataclasses

    from dopt.engine import SeqLMTrainer

    with pytest.raises(ValueError, match="attn"):
        SeqLMTrainer(_seqlm_cfg(attn="flash"))
    with pytest.raises(ValueError, match="divisible"):
        SeqLMTrainer(_seqlm_cfg(seq_len=100))
    with pytest.raises(ValueError, match="heads"):
        SeqLMTrainer(_seqlm_cfg(attn="ulysses", heads=6))
    with pytest.raises(ValueError, match="single-device"):
        SeqLMTrainer(_seqlm_cfg(attn="dense"))


@pytest.mark.slow  # ~15s/param: chunked fwd+bwd vs dense, both causalities
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_kv_chunked_exact(devices, causal):
    """Within-block KV chunking (flash-style) must be EXACT vs both the
    unchunked ring path and single-device dense attention — including
    gradients."""
    mesh = make_seq_mesh(8)
    q, k, v = _qkv(l=64)
    ref = dense_attention(q, k, v, causal=causal)
    for chunk in (2, 4, 8):
        out = ring_attention(q, k, v, mesh, causal=causal, kv_chunk=chunk)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-5)

    def loss_ring(args):
        return ring_attention(*args, mesh, causal=causal, kv_chunk=4).sum()

    def loss_dense(args):
        return dense_attention(*args, causal=causal).sum()

    g1 = jax.grad(loss_ring)((q, k, v))
    g2 = jax.grad(loss_dense)((q, k, v))
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)


def test_ring_attention_kv_chunk_validation(devices):
    mesh = make_seq_mesh(8)
    q, k, v = _qkv(l=64)
    with pytest.raises(ValueError, match="kv_chunk"):
        ring_attention(q, k, v, mesh, kv_chunk=3)  # doesn't divide block 8
    from tests.test_sequence import _seqlm_cfg
    from dopt.engine import SeqLMTrainer
    with pytest.raises(ValueError, match="kv_chunk"):
        SeqLMTrainer(_seqlm_cfg(attn="ulysses", heads=8, kv_chunk=4))
