"""Property-based invariants for the topology/mixing layer (hypothesis).

The example-based tests in test_topology.py pin the reference's exact
semantics; these sweep the (topology, mode, n) space for the structural
invariants every engine path relies on:

* row-stochasticity (consensus is an average, never a scale drift)
* zero diagonal without self_weight (reference semantics, SURVEY §6.2)
* doubly-stochastic modes also column-sum to 1
* dropout repair preserves row-stochasticity over the survivors
* shift_decomposition reconstructs circulant matrices exactly
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed; the hypothesis-free "
    "sweeps of the same invariants live in test_faults.py")
from hypothesis import given, settings, strategies as st  # noqa: E402

from dopt.topology import (build_mixing_matrices, repair_for_dropout,
                           shift_decomposition)

TOPOLOGIES = st.sampled_from(["circle", "star", "complete", "dynamic",
                              "random", "torus"])
MODES = st.sampled_from(["stochastic", "metropolis", "uniform"])
NS = st.integers(min_value=3, max_value=12)


@settings(max_examples=40, deadline=None)
@given(topology=TOPOLOGIES, mode=MODES, n=NS, seed=st.integers(0, 2**16))
def test_mixing_row_stochastic_and_zero_diag(topology, mode, n, seed):
    mm = build_mixing_matrices(topology, mode, n, seed=seed)
    assert mm.is_row_stochastic()
    if mode != "metropolis":  # metropolis keeps self-loops by construction
        for m in mm.matrices:
            diag = np.diag(m)
            off = m.sum(axis=1) - diag
            for i in range(m.shape[0]):
                if off[i] > 0:
                    # connected workers: reference zero-diagonal semantics
                    assert abs(diag[i]) < 1e-12
                else:
                    # isolated workers (dynamic/random single-edge rounds)
                    # keep their own weights — self-loop of exactly 1
                    # (the fix for the reference's zero-row NaN)
                    np.testing.assert_allclose(diag[i], 1.0, atol=1e-12)


@settings(max_examples=20, deadline=None)
@given(topology=st.sampled_from(["circle", "complete", "torus"]),
       n=st.integers(min_value=3, max_value=10),
       seed=st.integers(0, 2**16))
def test_double_stochastic_columns_sum_to_one(topology, n, seed):
    mm = build_mixing_matrices(topology, "double_stochastic", n, seed=seed)
    for m in mm.matrices:
        np.testing.assert_allclose(m.sum(axis=0), 1.0, atol=1e-6)
        np.testing.assert_allclose(m.sum(axis=1), 1.0, atol=1e-6)


@settings(max_examples=30, deadline=None)
@given(n=st.integers(min_value=3, max_value=12),
       seed=st.integers(0, 2**16),
       data=st.data())
def test_dropout_repair_keeps_survivor_rows_stochastic(n, seed, data):
    mm = build_mixing_matrices("complete", "metropolis", n, seed=seed)
    alive = np.asarray(
        data.draw(st.lists(st.sampled_from([0.0, 1.0]),
                           min_size=n, max_size=n)), np.float32)
    if alive.sum() == 0:
        alive[0] = 1.0  # engine guarantees at least one survivor
    w = repair_for_dropout(mm.matrices[0], alive)
    for i in range(n):
        if alive[i]:
            np.testing.assert_allclose(w[i].sum(), 1.0, atol=1e-6)
            # no weight flows from dead workers
            assert np.all(w[i][alive == 0.0] == 0.0)


@settings(max_examples=30, deadline=None)
@given(n=st.integers(min_value=3, max_value=10),
       seed=st.integers(0, 2**16))
def test_shift_decomposition_reconstructs_circulant(n, seed):
    rng = np.random.default_rng(seed)
    # random circulant built from a random first row
    row = rng.random(n)
    w = np.stack([np.roll(row, i) for i in range(n)])
    shifts = shift_decomposition(w)
    rec = np.zeros_like(w)
    for s, coeffs in shifts:
        for i in range(n):
            rec[i, (i + s) % n] += coeffs[i]
    np.testing.assert_allclose(rec, w, atol=1e-12)
