"""Per-layer/roofline analysis for baseline5 (32-worker ResNet-18 gossip
— the BASELINE.json north-star config).

Answers VERDICT r3 weak #4: is the measured MFU a CIFAR-spatial-conv
ceiling or recoverable?  Three numbers, all measured on the chip:

1. **Measured device time per round** — from the committed XLA trace
   (``results/trace_baseline5.json``, written by trace_roofline.py),
   which is immune to the host/tunnel wall-clock noise.
2. **Fleet-independence bound** — the same per-sample training step
   with ONE weight set at the same total batch (W=1, B=W·local_bs).
   No stacked-fleet engine can beat this: it removes the per-worker
   weights entirely, so the gap between it and (1) is the true cost of
   carrying 32 independent models (grouped-conv inefficiency at
   feature_group_count=32, per-worker GroupNorm, stacked head).
3. **MFU on the device-time basis** — samples/s·FLOPs/sample against
   the chip's bf16 peak, with FLOPs from XLA's own cost analysis.

Usage: python scripts/roofline_baseline5.py [--out results/roofline_baseline5.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def measure_w1_bound(batch: int, steps: int = 12) -> float:
    """Marginal per-step seconds for a single-weight-set ResNet-18
    training step at the fleet's total batch (the bound no stacked
    engine can beat)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dopt.models import build_model
    from dopt.models.losses import cross_entropy
    from dopt.optim import SGDState, sgd_step

    model = build_model("resnet18", faithful=False, dtype="bfloat16")
    p = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    m = jax.tree.map(jnp.zeros_like, p)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 32, 32, 3)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, batch).astype(np.int32))
    w = jnp.ones((batch,), jnp.float32)

    def one(p, m):
        def loss_fn(p_):
            return cross_entropy(model.apply({"params": p_}, x), y, w)
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, st = sgd_step(p, SGDState(m), g, lr=0.1, momentum=0.9)
        return p, st.momentum, loss

    def k_steps(p, m, k):
        def body(c, _):
            p_, m_, l = one(*c)
            return (p_, m_), l
        (p, m), ls = jax.lax.scan(body, (p, m), None, length=k)
        return ls.sum()

    f1 = jax.jit(lambda p, m: k_steps(p, m, 1))
    fk = jax.jit(lambda p, m: k_steps(p, m, steps))
    float(f1(p, m)); float(fk(p, m))

    def t(f):
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(f(p, m))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    return (t(fk) - t(f1)) / (steps - 1)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default="results/trace_baseline5.json")
    ap.add_argument("--out", default="results/roofline_baseline5.json")
    args = ap.parse_args()

    import jax

    from dopt.presets import get_preset
    from dopt.utils.profiling import device_peak_flops, train_flops_per_sample
    from dopt.models import build_model
    import jax.numpy as jnp

    trace = json.loads(Path(args.trace).read_text())
    rounds = trace.get("rounds_traced", 2)
    dev_ms_round = trace["device_self_time_us"] / 1e3 / rounds

    cfg = get_preset("baseline5")
    w = cfg.data.num_users
    shard = cfg.data.synthetic_train_size // w
    samples_round = w * shard * cfg.gossip.local_ep
    total_batch = w * cfg.gossip.local_bs

    model = build_model("resnet18", faithful=False)
    p0 = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    tfps = train_flops_per_sample(
        lambda p, x: model.apply({"params": p}, x), p0, (32, 32, 3))
    kind, peak = device_peak_flops()

    sps_dev = samples_round / (dev_ms_round / 1e3)
    flops_sec = sps_dev * tfps

    w1_step = measure_w1_bound(total_batch)
    steps_round = -(-shard // cfg.gossip.local_bs) * cfg.gossip.local_ep
    w1_ms_round = w1_step * steps_round * 1e3
    w1_sps = samples_round / (w1_ms_round / 1e3)

    out = {
        "preset": "baseline5",
        "model": "resnet18", "workers": w, "local_bs": cfg.gossip.local_bs,
        "device_kind": kind,
        "train_flops_per_sample": round(tfps),
        "measured": {
            "device_ms_per_round": round(dev_ms_round, 1),
            "samples_per_sec_device_basis": round(sps_dev, 1),
            "model_tflops_per_sec": round(flops_sec / 1e12, 2),
            "mfu_vs_bf16_peak": round(flops_sec / peak, 4) if peak else None,
            "source": f"{args.trace} (XLA device self-time; host/tunnel "
                      "noise excluded)",
        },
        "fleet_independence_bound": {
            "w1_ms_per_step": round(w1_step * 1e3, 2),
            "w1_ms_per_round_equiv": round(w1_ms_round, 1),
            "w1_samples_per_sec": round(w1_sps, 1),
            "w1_mfu_vs_bf16_peak": round(w1_sps * tfps / peak, 4)
                                    if peak else None,
            "measured_fraction_of_bound": round(w1_ms_round / dev_ms_round, 3),
            "method": "single weight set, batch = W*local_bs, marginal "
                      "per-step time of a fused scan — removes the "
                      "per-worker-weights cost entirely; no stacked "
                      "fleet can exceed this throughput",
        },
        "conv_pct_of_device": next(
            (c["pct_of_device"] for c in trace["device_categories"]
             if c["op_type"] == "conv_general_dilated"), None),
        "history_vmap_r3_device_ms_per_round": 2754.4,
        "conclusion": (
            "The grouped-stacked fleet forward (worker axis in conv "
            "feature groups) runs the 32-model round at "
            f"{dev_ms_round:.0f} ms of device time vs 2754 ms for the "
            "vmapped per-worker path (r3).  Round 5's per-layer table "
            "(results/roofline_layers_baseline5.json) showed the "
            "grouped-conv penalty is LANE-BATCH STARVATION, not a "
            "hardware ceiling: at the old local_bs=64 the "
            "stride-2/1x1/deep-stage convs ran at ~0.35x of their "
            "single-weight-set rate, recovering to ~0.9x at 128 "
            "rows/lane.  With local_bs=128 in the preset the fleet "
            "program stands at the fraction of the single-weight-set "
            "bound reported in "
            "fleet_independence_bound.measured_fraction_of_bound."),
    }
    Path(args.out).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: out[k] for k in ("measured",
                                          "fleet_independence_bound")},
                     indent=1))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
