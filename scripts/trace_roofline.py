"""Profiler-trace-backed roofline evidence for the benchmark configs.

Captures a real XLA profiler trace (``dopt.utils.profiling.trace``) of a
steady-state fused round block, then reduces the xplane to a committed
JSON summary: per-op-category self time, the top ops, and the
device/host split.  This is the evidence layer behind the MFU numbers
in ``results/bench_suite.json`` and ``BENCH_r*.json`` — the prose
roofline claims ("activation-bandwidth-bound", "conv1 has 1 input
channel") become checkable op-level timings.

Targets: ``--preset baseline5`` (32-worker ResNet-18 gossip, the north
star) and ``--preset headline`` (bench.py's 6-worker Model1 workload).

Writes results/trace_<name>.json (the raw xplane stays out of git — it
is hundreds of KB of protobuf; the summary carries the numbers).

Usage: python scripts/trace_roofline.py --preset baseline5 [--rounds 3]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_trainer(preset: str):
    from dopt.engine import FederatedTrainer, GossipTrainer

    if preset == "headline":
        import bench

        cfg = bench._config(fast=True, train_size=60_000, test_size=10_000)
    else:
        from dopt.presets import get_preset

        cfg = get_preset(preset)
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, compute_dtype="bfloat16"),
            data=dataclasses.replace(cfg.data, plan_impl="native"),
        )
    is_gossip = cfg.gossip is not None
    trainer = (GossipTrainer if is_gossip else FederatedTrainer)(
        cfg, eval_every=10_000)   # no eval inside the traced window
    return cfg, trainer


def summarize_xplane(trace_dir: str) -> dict:
    """Reduce the captured xplane to category/op-level self times
    (shared reduction: ``dopt.utils.profiling.xplane_op_stats``)."""
    from dopt.utils.profiling import xplane_op_stats

    return xplane_op_stats(trace_dir)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="baseline5",
                    help="baseline1..5 or 'headline' (bench.py workload)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="rounds inside the traced fused block")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from dopt.utils.profiling import trace

    cfg, trainer = build_trainer(args.preset)
    rounds = args.rounds
    trainer.run(rounds=rounds, block=rounds)          # compile + warmup
    import jax

    with tempfile.TemporaryDirectory(prefix="dopt-trace-") as td:
        t0 = time.perf_counter()
        with trace(td):
            trainer.run(rounds=rounds, block=rounds)
            jax.block_until_ready(trainer.params)
        elapsed = time.perf_counter() - t0
        summary = summarize_xplane(td)

    payload = {
        "preset": args.preset,
        "config_name": cfg.name,
        "model": cfg.model.model,
        "workers": cfg.data.num_users,
        "rounds_traced": rounds,
        "wall_seconds_traced": round(elapsed, 3),
        "device": str(jax.devices()[0]),
        **summary,
    }
    out = Path(args.out or f"results/trace_{args.preset}.json")
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    top = payload["device_categories"][:5]
    print(f"{args.preset}: {rounds} rounds traced in {elapsed:.2f}s; "
          f"device self-time {payload['device_self_time_us']/1e6:.3f}s")
    for c in top:
        print(f"  {c['op_type']:<28s} {c['pct_of_device']:6.2f}%")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
