"""Host-side span tracing with Chrome-trace (Perfetto) export.

``SpanTracer.span("block")`` is a nestable context manager that records
(name, start, duration, depth) against the tracer's epoch.  The engines
do not call it directly: ``dopt.utils.profiling.PhaseTimers`` grew a
``tracer`` hook, so attaching telemetry to a trainer
(``dopt.obs.attach``) instruments every existing ``timers.phase(...)``
/ ``timers.measure(...)`` site — host batch planning, the fused block
dispatch, checkpoint writes — with zero run-loop changes, and callers
can open extra spans (``telemetry.span("eval")``) around anything else.

``write_chrome`` emits the ``{"traceEvents": [...]}`` JSON the Chrome
tracing UI / Perfetto / TensorBoard's trace viewer load directly
(complete ``"ph": "X"`` events on one track; nesting is by time
containment).  This is the HOST-side companion to the XLA trace from
``dopt.utils.profiling.trace`` — spans show where the round loop's wall
clock went, the XLA trace shows what the device did inside it.
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import deque
from pathlib import Path
from typing import Any, Iterator

# Spans accrue a few records per round for as long as telemetry is
# attached — a million-round metrics-only run must not leak host memory
# into a trace nobody asked for, so the record list is a bounded ring
# (the Chrome export carries the most recent spans; per-name totals
# accumulate exactly regardless of eviction).
DEFAULT_SPAN_CAPACITY = 100_000


class SpanTracer:
    """Accumulates nested host spans; cheap enough to leave attached."""

    def __init__(self, clock=time.perf_counter,
                 capacity: int | None = DEFAULT_SPAN_CAPACITY):
        self._clock = clock
        self._t0 = clock()
        self._depth = 0
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._totals: dict[str, float] = {}

    @property
    def spans(self) -> list[dict[str, Any]]:
        return list(self._ring)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        self._depth += 1
        depth = self._depth - 1
        try:
            yield
        finally:
            self._depth -= 1
            t1 = self._clock()
            name = str(name)
            self._ring.append({
                "name": name,
                "ts_us": (t0 - self._t0) * 1e6,
                "dur_us": (t1 - t0) * 1e6,
                "depth": depth,
            })
            self._totals[name] = (self._totals.get(name, 0.0)
                                  + (t1 - t0))

    def totals(self) -> dict[str, float]:
        """Per-name wall-clock seconds (PhaseTimers-shaped summary);
        exact even after ring eviction."""
        return dict(self._totals)

    def to_chrome(self) -> list[dict[str, Any]]:
        """Chrome-trace complete events, sorted by start time."""
        return [
            {"name": s["name"], "cat": "dopt", "ph": "X", "pid": 0,
             "tid": 0, "ts": round(s["ts_us"], 3),
             "dur": round(s["dur_us"], 3)}
            for s in sorted(self.spans, key=lambda s: s["ts_us"])
        ]

    def write_chrome(self, path: str | Path) -> Path:
        from dopt.utils.metrics import atomic_write_text

        payload = {"traceEvents": self.to_chrome(),
                   "displayTimeUnit": "ms"}
        return atomic_write_text(path, json.dumps(payload))
