"""Client population registry (dopt.population): cohort sampling over
1k–10k clients with hierarchical (multi-wave) aggregation.

Tier-1 pins, in dependency order:

* registry units — shard assignment, orphan adoption, stateless sampler
  determinism (a freshly constructed registry redraws the identical
  cohorts — the restart contract), digest stability, binding shapes;
* the cohort-vs-flat PARITY contract: a 64-client population with
  cohort 64 on 8 lanes × 8 waves reproduces the 64-lane flat engine's
  aggregate to f32-allclose (momentum 0 — population clients are
  stateless; the flat run's zero-momentum update is too, so the two
  paths differ only by summation association);
* per-client quarantine persistence across cohorts (adversaries are
  CLIENT ids, not lane slots);
* mid-run kill-and-resume bit-identity (stateless sampler + registry
  state in the checkpoint);
* the ``cohort`` ledger kind round-trips through
  ``History.faults_from_json`` like every fault kind.

Engine runs use the mlp model + tiny synthetic data (tier-1 budget);
the 10k-client sweep is marked slow.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig, PopulationConfig, RobustConfig)
from dopt.data.partition import (assign_client_shards,
                                 orphan_shard_adopters)
from dopt.data.pipeline import make_batch_plan
from dopt.population import (ClientRegistry, cohort_digest,
                             validate_population_config)
from dopt.utils.metrics import History

pytestmark = pytest.mark.population


# ---------------------------------------------------------------------
# Config helpers (mlp + tiny synthetic data — tier-1 budget)
# ---------------------------------------------------------------------

def _fed_cfg(*, clients, cohort, lanes=None, num_users=8, seed=7,
             momentum=0.5, train=320, rounds=3, faults=None, robust=None,
             local_bs=16, pop_seed=None, algorithm="fedavg"):
    return ExperimentConfig(
        name="test-pop", seed=seed,
        data=DataConfig(dataset="synthetic", num_users=num_users, iid=True,
                        synthetic_train_size=train,
                        synthetic_test_size=64),
        model=ModelConfig(model="mlp", faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=momentum),
        federated=FederatedConfig(algorithm=algorithm, frac=0.5,
                                  rounds=rounds, local_ep=1,
                                  local_bs=local_bs),
        faults=faults, robust=robust,
        population=PopulationConfig(clients=clients, cohort=cohort,
                                    lanes=lanes, seed=pop_seed),
    )


def _train(cfg, rounds):
    from dopt.engine.federated import FederatedTrainer

    tr = FederatedTrainer(cfg)
    tr.run(rounds=rounds)
    return tr


# ---------------------------------------------------------------------
# Registry units
# ---------------------------------------------------------------------

def test_assign_client_shards():
    a = assign_client_shards(10, 4)
    assert a.tolist() == [0, 1, 2, 3, 0, 1, 2, 3, 0, 1]
    # population == shards -> the identity map (the parity contract's
    # precondition).
    assert assign_client_shards(6, 6).tolist() == list(range(6))
    r = assign_client_shards(1000, 16, seed=3, mode="random")
    counts = np.bincount(r, minlength=16)
    assert counts.max() - counts.min() <= 1           # still balanced
    assert not np.array_equal(r, assign_client_shards(1000, 16))
    assert np.array_equal(r, assign_client_shards(1000, 16, seed=3,
                                                  mode="random"))
    with pytest.raises(ValueError, match="unknown client-shard"):
        assign_client_shards(4, 2, mode="hash")
    with pytest.raises(ValueError, match="population"):
        assign_client_shards(0, 2)


def test_orphan_shard_adopters():
    # 6 clients on 3 shards; shard 1's clients (1, 4) both away.
    assignment = assign_client_shards(6, 3)
    alive = np.array([True, False, True, True, False, True])
    assert orphan_shard_adopters(assignment, alive, 3) == {1: 2}
    # Everyone alive / everyone away -> no adoption.
    assert orphan_shard_adopters(assignment, np.ones(6, bool), 3) == {}
    assert orphan_shard_adopters(assignment, np.zeros(6, bool), 3) == {}


def test_sampler_determinism_across_restarts():
    pop = PopulationConfig(clients=200, cohort=16)
    a = ClientRegistry(pop, num_shards=8, seed=11)
    b = ClientRegistry(pop, num_shards=8, seed=11)   # "restarted" process
    for t in range(5):
        ca, cb = a.sample_cohort(t), b.sample_cohort(t)
        assert np.array_equal(ca, cb)
        assert len(np.unique(ca)) == 16              # without replacement
    assert not np.array_equal(a.sample_cohort(0), a.sample_cohort(1))
    # A different sampler seed redraws a different stream.
    c = ClientRegistry(PopulationConfig(clients=200, cohort=16, seed=99),
                       num_shards=8, seed=11)
    assert not np.array_equal(a.sample_cohort(0), c.sample_cohort(0))


def test_sampler_respects_eligibility():
    pop = PopulationConfig(clients=20, cohort=8)
    reg = ClientRegistry(pop, num_shards=4, seed=0)
    reg.quarantine_until[:15] = 100                  # only 5 eligible
    cohort = reg.sample_cohort(0)
    assert len(cohort) == 5                          # size is data
    assert (cohort >= 15).all()
    reg.quarantine_until[:] = 100
    assert len(reg.sample_cohort(0)) == 0            # empty round, no error


def test_cohort_digest_and_binding():
    ids = np.array([5, 2, 9])
    assert cohort_digest(ids) == cohort_digest(ids[::-1])
    assert cohort_digest(ids) != cohort_digest(np.array([5, 2, 8]))
    reg = ClientRegistry(PopulationConfig(clients=40, cohort=10, lanes=4),
                         num_shards=4, seed=0)
    assert reg.waves == 3
    b = reg.bind(0, np.arange(10), np.array([7, 3, 9, 1, 5]))
    assert b.lane_ids.shape == (3, 4) and b.valid.shape == (3, 4)
    flat = b.lane_ids.reshape(-1)
    assert flat[:5].tolist() == [1, 3, 5, 7, 9]      # survivors, sorted
    assert b.valid.reshape(-1)[:5].tolist() == [1.0] * 5
    assert b.valid.reshape(-1)[5:].tolist() == [0.0] * 7
    assert set(flat[5:]) <= {1, 3, 5, 7, 9}          # wraparound padding
    row = b.ledger_row(40)
    assert row["kind"] == "cohort" and row["worker"] == -1
    assert "waves_3" in row["action"] and "of_40" in row["action"]


def test_validate_population_config():
    with pytest.raises(ValueError, match="cohort"):
        validate_population_config(PopulationConfig(clients=4, cohort=8))
    with pytest.raises(ValueError, match="clients"):
        validate_population_config(PopulationConfig(clients=0))
    with pytest.raises(ValueError, match="lanes"):
        validate_population_config(PopulationConfig(lanes=0))


def test_population_churn_ledger_rows():
    """Churn rows are population-keyed: per-CLIENT leave/rejoin plus
    per-SHARD adoptions from the map ``plan_matrix_for`` actually
    applies — never the worker-level ``adopters_for`` fabrication
    (which assumes worker i owns shard i)."""
    pop = PopulationConfig(clients=6, cohort=2)
    reg = ClientRegistry(pop, num_shards=3, seed=0,
                         faults=FaultConfig(churn=0.5))
    # Synthetic round: shard 1's clients (ids 1, 4) both away.
    away = np.array([False, True, False, False, True, False])
    rows = reg.churn_ledger_rows(0, away)
    assert {r["action"] for r in rows if r["worker"] >= 0} == {"left"}
    assert {r["worker"] for r in rows if r["action"] == "left"} == {1, 4}
    adopt = [r for r in rows if r["worker"] == -1]
    assert adopt == [{"round": 0, "worker": -1, "kind": "churn",
                      "action": "shard_1_adopted_by_2"}]
    # A healthy fleet (clients away but every shard still covered)
    # ledgers NO adoption rows.
    away1 = np.array([False, True, False, False, False, False])
    rows1 = reg.churn_ledger_rows(0, away1)
    assert not [r for r in rows1 if "adopted" in r["action"]]
    # End to end: a churned population run's ledger never carries the
    # worker-level 'shard_adopted_by' fabrication (client id in the
    # adopter field), only shard-level rows.
    cfg = _fed_cfg(clients=50, cohort=8, lanes=8,
                   faults=FaultConfig(churn=0.2, churn_span=2))
    tr = _train(cfg, 3)
    for r in tr.history.faults:
        if r["kind"] == "churn" and "adopted" in r["action"]:
            assert r["worker"] == -1 and r["action"].startswith("shard_")


def test_registry_state_roundtrip():
    pop = PopulationConfig(clients=30, cohort=6)
    a = ClientRegistry(pop, num_shards=6, seed=1)
    a.record_participation(3, np.array([4, 7, 9]))
    a.screen_streak[4] = 2
    a.quarantine_until[7] = 11
    b = ClientRegistry(pop, num_shards=6, seed=1)
    b.load_state(a.state_dict())
    assert np.array_equal(a.participation, b.participation)
    assert np.array_equal(a.last_sampled, b.last_sampled)
    assert np.array_equal(a.screen_streak, b.screen_streak)
    assert np.array_equal(a.quarantine_until, b.quarantine_until)
    # Mismatched geometry is rejected loudly.
    c = ClientRegistry(PopulationConfig(clients=30, cohort=8),
                       num_shards=6, seed=1)
    with pytest.raises(ValueError, match="cohort"):
        c.load_state(a.state_dict())


def test_batch_plan_rows_keyed_by_client_id():
    m = np.arange(6 * 12, dtype=np.int64).reshape(6, 12)
    full = make_batch_plan(m, batch_size=4, local_ep=1, seed=5, round_idx=2)
    # Client ids == row ids -> bit-identical to the full plan's rows.
    sub = make_batch_plan(m, batch_size=4, local_ep=1, seed=5, round_idx=2,
                          workers=np.array([1, 4]), rows=np.array([1, 4]))
    assert np.array_equal(sub.idx, full.idx[[1, 4]])
    # Two clients sharing one shard draw DISTINCT client-keyed streams
    # over the same rows.
    shared = make_batch_plan(m, batch_size=4, local_ep=1, seed=5,
                             round_idx=2, workers=np.array([10, 11]),
                             rows=np.array([2, 2]))
    assert sorted(shared.idx[0].ravel()) == sorted(shared.idx[1].ravel())
    assert not np.array_equal(shared.idx[0], shared.idx[1])
    with pytest.raises(ValueError, match="rows= requires workers="):
        make_batch_plan(m, batch_size=4, rows=np.array([0]))


# ---------------------------------------------------------------------
# Federated engine: parity, determinism, quarantine, resume
# ---------------------------------------------------------------------

def test_cohort_vs_flat_parity():
    """A full-population cohort (64 clients == 64 shards) on 8 lanes ×
    8 waves reproduces the 64-lane flat engine's aggregate to
    f32-allclose — hierarchical aggregation changes summation order,
    never the math (the acceptance pin)."""
    from dopt.engine.federated import FederatedTrainer

    base = dict(
        name="parity", seed=11,
        data=DataConfig(dataset="synthetic", num_users=64, iid=True,
                        synthetic_train_size=320, synthetic_test_size=64),
        model=ModelConfig(model="mlp", faithful=False),
        # momentum 0: the flat engine's per-worker momentum buffer then
        # carries nothing round to round, matching the population
        # clients' statelessness.
        optim=OptimizerConfig(lr=0.05, momentum=0.0),
        federated=FederatedConfig(algorithm="fedavg", frac=1.0, rounds=2,
                                  local_ep=1, local_bs=8),
    )
    # eval_train=False: the 64-lane per-worker train eval is the flat
    # engine's costliest compile and irrelevant to the aggregate pin.
    flat = FederatedTrainer(ExperimentConfig(**base), eval_train=False)
    hf = flat.run(rounds=2)
    pop = FederatedTrainer(ExperimentConfig(
        **base, population=PopulationConfig(clients=64, cohort=64,
                                            lanes=8)), eval_train=False)
    hp = pop.run(rounds=2)
    assert pop._registry.waves == 8
    for a, b in zip(jax.tree.leaves(jax.device_get(flat.theta)),
                    jax.tree.leaves(jax.device_get(pop.theta))):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    for rf, rp in zip(hf.rows, hp.rows):
        assert rf["test_acc"] == pytest.approx(rp["test_acc"], abs=1e-6)


@pytest.fixture(scope="module")
def pop_pair():
    """Two independently trained population runs of one config — shared
    by the determinism / ledger / JSON-round-trip pins (tier-1 budget:
    one compile pair instead of one per test)."""
    cfg = _fed_cfg(clients=50, cohort=20, lanes=8)
    return _train(cfg, 3), _train(cfg, 3)


def test_population_run_deterministic(pop_pair):
    a, b = pop_pair
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        assert np.array_equal(x, y)                  # bit-identical
    assert a.history.faults == b.history.faults
    assert np.array_equal(a._registry.participation,
                          b._registry.participation)


def test_cohort_ledger_rows_and_counts(pop_pair):
    tr = pop_pair[0]
    cohort_rows = [r for r in tr.history.faults if r["kind"] == "cohort"]
    assert len(cohort_rows) == 3
    for t, r in enumerate(cohort_rows):
        assert r["round"] == t and r["worker"] == -1
        assert "sampled_20_of_50" in r["action"]
        assert "waves_3" in r["action"]              # ceil(20/8)
    assert tr._registry.participation.sum() == 60
    assert {"cohort": 20, "population": 50}.items() <= \
        tr.history.rows[0].items()


def test_quarantine_persists_across_cohorts():
    """Adversaries are CLIENT ids: corrupt_max pins clients 0..1 as
    persistent nan-liars, the screen catches them in whichever cohort
    samples them, and the quarantine sentence follows the client —
    while sentenced it is never sampled, and it is readmitted after."""
    cfg = _fed_cfg(
        clients=12, cohort=8, lanes=8, num_users=8, rounds=0,
        faults=FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode="nan"),
        robust=RobustConfig(quarantine_after=1, quarantine_rounds=3))
    from dopt.engine.federated import FederatedTrainer

    tr = FederatedTrainer(cfg)
    reg = tr._registry
    for t in range(8):
        quarantined_before = set(np.nonzero(reg.quarantine_until > t)[0])
        tr.run(rounds=1)
        for c in quarantined_before:                 # never sampled while
            assert reg.last_sampled[c] != t          # serving a sentence
    ledger = tr.history.faults
    sentenced = {r["worker"] for r in ledger
                 if r["kind"] == "quarantine"
                 and r["action"].startswith("quarantined_until")}
    assert sentenced and sentenced <= {0, 1}         # only the pinned liars
    screened = {r["worker"] for r in ledger
                if r["action"] == "screened_nonfinite"}
    assert screened == sentenced
    assert any(r["kind"] == "quarantine" and r["action"] == "readmitted"
               for r in ledger)                      # sentences expire
    # The nan lies never reached theta.
    assert all(np.isfinite(x).all()
               for x in jax.tree.leaves(jax.device_get(tr.theta)))


def test_kill_and_resume_bit_identity(tmp_path):
    from dopt.engine.federated import FederatedTrainer

    cfg = _fed_cfg(clients=50, cohort=20, lanes=8,
                   robust=RobustConfig(quarantine_after=2,
                                       quarantine_rounds=3))
    cont = _train(cfg, 3)
    killed = FederatedTrainer(cfg)
    killed.run(rounds=2)
    killed.save(tmp_path / "ckpt")
    resumed = FederatedTrainer(cfg)
    resumed.restore(tmp_path / "ckpt")
    assert resumed.round == 2
    resumed.run(rounds=1)
    for x, y in zip(jax.tree.leaves(jax.device_get(cont.theta)),
                    jax.tree.leaves(jax.device_get(resumed.theta))):
        assert np.array_equal(x, y)
    assert cont.history.rows == resumed.history.rows
    assert cont.history.faults == resumed.history.faults
    assert np.array_equal(cont._registry.participation,
                          resumed._registry.participation)
    assert np.array_equal(cont._registry.last_sampled,
                          resumed._registry.last_sampled)


def test_restore_rejects_laneengine_checkpoint(tmp_path):
    from dopt.engine.federated import FederatedTrainer

    plain = _fed_cfg(clients=50, cohort=20, lanes=8).replace(population=None)
    tr = FederatedTrainer(plain)
    tr.save(tmp_path / "ckpt")     # round 0 — no compile, state suffices
    pop = FederatedTrainer(_fed_cfg(clients=50, cohort=20, lanes=8))
    with pytest.raises(ValueError, match="population_registry"):
        pop.restore(tmp_path / "ckpt")


def test_cohort_ledger_json_roundtrip(pop_pair, tmp_path):
    tr = pop_pair[0]
    path = tmp_path / "faults.json"
    tr.history.faults_to_json(path)
    back = History.faults_from_json(path)
    assert back == tr.history.faults                 # row-for-row
    assert any(r["kind"] == "cohort" for r in back)


# ---------------------------------------------------------------------
# Eligibility / rejection matrix
# ---------------------------------------------------------------------

@pytest.mark.parametrize("section, field, value, match", [
    ("federated", "algorithm", "scaffold", "stateless-client"),
    ("data", "local_holdout", 0.1, "holdout"),
    ("federated", "compact", True, "compact"),
    ("federated", "staleness_max", 2, "staleness"),
    ("federated", "comm_dtype", "bfloat16", "comm_dtype"),
    ("federated", "update_sharding", "scatter", "scatter"),
    ("robust", "aggregator", "median", "aggregator"),
])
def test_population_rejections(section, field, value, match):
    import dataclasses

    from dopt.engine.federated import FederatedTrainer

    cfg = _fed_cfg(clients=50, cohort=20, lanes=8)
    sub = getattr(cfg, section) or RobustConfig()
    cfg = cfg.replace(**{section: dataclasses.replace(sub,
                                                      **{field: value})})
    with pytest.raises(ValueError, match=match):
        FederatedTrainer(cfg)


def test_population_rejects_stale_corrupt():
    from dopt.engine.federated import FederatedTrainer

    cfg = _fed_cfg(clients=50, cohort=20, lanes=8,
                   faults=FaultConfig(corrupt=0.5, corrupt_mode="stale"))
    with pytest.raises(ValueError, match="stateless"):
        FederatedTrainer(cfg)


# ---------------------------------------------------------------------
# Gossip engine: cohort→lane data binding
# ---------------------------------------------------------------------

def _gossip_cfg(**pop_kw):
    return ExperimentConfig(
        name="test-gpop", seed=5,
        data=DataConfig(dataset="synthetic", num_users=4, iid=True,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=3, local_ep=1,
                            local_bs=32),
        population=PopulationConfig(**pop_kw) if pop_kw else None,
    )


def test_gossip_population_binding_blocked_parity():
    from dopt.engine.gossip import GossipTrainer

    cfg = _gossip_cfg(clients=24, cohort=4)
    a = GossipTrainer(cfg)
    a.run(rounds=2, block=1)
    b = GossipTrainer(cfg)
    b.run(rounds=2, block=2)
    rows_a = [r for r in a.history.faults if r["kind"] == "cohort"]
    rows_b = [r for r in b.history.faults if r["kind"] == "cohort"]
    assert len(rows_a) == 2 and rows_a == rows_b     # identical binding
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        assert np.array_equal(x, y)                  # bit-identical
    assert a._registry.participation.sum() == 8


def test_gossip_population_rejections():
    import dataclasses

    from dopt.engine.gossip import GossipTrainer

    with pytest.raises(ValueError, match="cohort == data.num_users"):
        GossipTrainer(_gossip_cfg(clients=24, cohort=8))
    cfg = _gossip_cfg(clients=24, cohort=4)
    with pytest.raises(ValueError, match="client-keyed faults"):
        GossipTrainer(dataclasses.replace(
            cfg, faults=FaultConfig(crash=0.1)))


# ---------------------------------------------------------------------
# Presets / CLI wiring
# ---------------------------------------------------------------------

def test_xclients_preset():
    from dopt.presets import get_preset

    cfg = get_preset("baseline3-xclients")
    assert cfg.population is not None
    assert cfg.population.clients == 1000
    assert cfg.population.cohort == 64
    assert cfg.federated is not None                 # still baseline3
    assert cfg.data.num_users == 16


def test_cli_population_flags():
    from dopt.run import main

    # --cohort without --clients (and no population preset) is rejected.
    with pytest.raises(SystemExit, match="--clients"):
        main(["--preset", "baseline3", "--cohort", "32"])
    # Invalid combination is caught by the shared validator.
    with pytest.raises(SystemExit, match="cohort"):
        main(["--preset", "baseline3", "--clients", "10",
              "--cohort", "64"])


# ---------------------------------------------------------------------
# Heavy sweep (slow)
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_population_10k_sweep():
    """10k-client registry end to end: 256-client cohorts on 16 lanes
    (16 waves), two rounds — the client-scale regime the bench
    headline measures."""
    cfg = _fed_cfg(clients=10_000, cohort=256, lanes=16, num_users=16,
                   train=640, local_bs=8)
    tr = _train(cfg, 2)
    reg = tr._registry
    assert reg.waves == 16
    assert reg.participation.sum() == 512
    assert (reg.participation <= 2).all()            # without replacement
    rows = [r for r in tr.history.faults if r["kind"] == "cohort"]
    assert len(rows) == 2
    assert all("sampled_256_of_10000" in r["action"] for r in rows)
    assert all(np.isfinite(x).all()
               for x in jax.tree.leaves(jax.device_get(tr.theta)))
