"""Unified telemetry (dopt.obs): schema, sinks, spans, and the stream
invariants the subsystem owes the engines.

The heavy contracts, all tier-1-lean (mlp, tiny synthetic data, few
rounds; trainer builds are shared via module fixtures because each
build recompiles its round programs):

* schema validation of every event kind (and rejection of malformed
  events);
* blocked-vs-per-round event-stream equality on a chaos cocktail, both
  engines (the streams derive from the same host-replay data at the
  same post-fetch points, so fused execution is not a different
  experiment);
* kill-and-resume watermark continuity: the resumed run APPENDS to the
  dead run's JSONL and the merged stream carries every round exactly
  once;
* telemetry-off bit-identity: attaching telemetry changes nothing
  about the training trace (History rows + fault ledger) — the off
  path is the exact pre-change loop;
* graceful profiler degrade: a failing xplane reduction returns
  partial stats + a warning event instead of raising mid-bench.
"""

from __future__ import annotations

import json
import math

import pytest

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig)
from dopt.obs import (JsonlSink, MemorySink, PrometheusSink, SpanTracer,
                      Telemetry, attach, canonical, check_stream,
                      make_event, validate_event)
from dopt.utils.metrics import History

_DATA = DataConfig(dataset="synthetic", num_users=8, iid=True,
                   synthetic_train_size=256, synthetic_test_size=64)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5)
_ROUNDS = 6


def _fed_cfg() -> ExperimentConfig:
    """Federated chaos cocktail routing through the fused chaos-block
    path (staleness buffer as scan carry) with nan liars and a drop
    deadline — the hardest emission path to keep deterministic."""
    return ExperimentConfig(
        name="obs-fed", seed=11, data=_DATA, model=_MODEL, optim=_OPTIM,
        federated=FederatedConfig(algorithm="fedavg", frac=0.5,
                                  rounds=_ROUNDS, local_ep=1, local_bs=32,
                                  staleness_max=2, staleness_decay=0.5),
        faults=FaultConfig(crash=0.1, straggle=0.4, straggle_frac=0.5,
                           straggler_policy="drop", over_select=0.3,
                           corrupt=0.2, corrupt_mode="nan",
                           msg_delay=0.2, msg_delay_max=2))


def _gossip_cfg() -> ExperimentConfig:
    """Gossip link-mode cocktail (push-sum + drops/delays/churn) — the
    mass/staleness-buffer scan-carry blocked path."""
    return ExperimentConfig(
        name="obs-gossip", seed=11, data=_DATA, model=_MODEL, optim=_OPTIM,
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=_ROUNDS, local_ep=1,
                            local_bs=32, correction="push_sum"),
        faults=FaultConfig(crash=0.1, straggle=0.2, straggle_frac=0.5,
                           msg_drop=0.2, msg_delay=0.2, msg_delay_max=2,
                           churn=0.05, churn_span=2))


def _trainer(cfg: ExperimentConfig):
    if cfg.federated is not None:
        from dopt.engine.federated import FederatedTrainer

        return FederatedTrainer(cfg)
    from dopt.engine.gossip import GossipTrainer

    return GossipTrainer(cfg)


@pytest.fixture(scope="module")
def fed_continuous():
    """One telemetry-attached continuous federated run, shared by the
    equality / resume / off-identity tests (each build recompiles)."""
    tr = _trainer(_fed_cfg())
    mem = MemorySink()
    attach(tr, Telemetry([mem]), fresh=True)
    h = tr.run(rounds=_ROUNDS)
    return h, mem.events


@pytest.fixture(scope="module")
def gossip_continuous():
    tr = _trainer(_gossip_cfg())
    mem = MemorySink()
    attach(tr, Telemetry([mem]), fresh=True)
    h = tr.run(rounds=_ROUNDS)
    return h, mem.events


# ---------------------------------------------------------------- schema
def test_every_event_kind_validates():
    events = [
        make_event("run", engine="federated", name="x", round=0, workers=8),
        make_event("round", round=0, engine="federated",
                   metrics={"round": 0, "test_acc": 0.5, "note": "s",
                            "skipped": None}),
        make_event("gauge", round=0, name="quarantine_active", value=1.0),
        make_event("fault", round=0, worker=3, fault="crash",
                   action="dropped_from_round"),
        make_event("fault", round=0, worker=-1, fault="cohort",
                   action="sampled_64_of_1000"),  # fleet-level row
        make_event("phase", round=4, fractions={"conv": 0.5, "comm": 0.3,
                                                "update": 0.1,
                                                "other": 0.1}),
        make_event("bench", metrics={"value": 2.5, "unit": "rounds/sec",
                                     "quick": True, "na": None}),
        make_event("warning", message="xplane reduction failed",
                   source="device_stats_of"),
    ]
    for ev in events:
        validate_event(ev)
    s = check_stream(events)
    assert s["events"] == len(events) and s["rounds"] == 1


@pytest.mark.parametrize("bad", [
    "not-an-object",
    {"v": 99, "kind": "round", "ts": 0.0},                 # bad version
    {"v": 1, "kind": "nope", "ts": 0.0},                   # unknown kind
    {"v": 1, "kind": "round", "ts": 0.0},                  # missing fields
    {"v": 1, "kind": "round", "ts": 0.0, "round": 0, "engine": "g",
     "metrics": {"x": float("nan")}},                      # non-finite
    {"v": 1, "kind": "gauge", "ts": 0.0, "round": 0, "name": "",
     "value": 1.0},                                        # empty name
    {"v": 1, "kind": "fault", "ts": 0.0, "round": 0, "worker": -2,
     "fault": "crash", "action": "x"},                     # worker < -1
    {"v": 1, "kind": "phase", "ts": 0.0, "fractions": {"conv": 1.5}},
])
def test_malformed_events_rejected(bad):
    with pytest.raises(ValueError):
        validate_event(bad)


def test_round_continuity_enforced():
    evs = [make_event("run", engine="g", name="x", round=0),
           make_event("round", round=0, engine="g", metrics={}),
           make_event("round", round=2, engine="g", metrics={})]
    with pytest.raises(ValueError, match="round sequence broken"):
        check_stream(evs)
    # a run header legitimately restarts the sequence (new segment)
    evs = [make_event("run", engine="g", name="x", round=0),
           make_event("round", round=0, engine="g", metrics={}),
           make_event("run", engine="f", name="y", round=0),
           make_event("round", round=0, engine="f", metrics={})]
    assert check_stream(evs)["segments"] == 2


# ----------------------------------------------------------------- sinks
def test_jsonl_sink_roundtrip_watermark_and_truncation(tmp_path):
    p = tmp_path / "m.jsonl"
    t = Telemetry.to_jsonl(p)
    t.emit("run", engine="g", name="x", round=0)
    t.emit_round_bundle(0, engine="g", metrics={"a": 1.0},
                        faults=[{"round": 0, "worker": 1, "kind": "crash",
                                 "action": "skipped_round"}],
                        gauges={"g1": 2.0})
    t.emit_round_bundle(1, engine="g", metrics={"a": 0.5})
    t.close()
    assert JsonlSink.scan_watermark(p) == 1
    # a kill can truncate the FINAL line; read() must drop it silently
    with open(p, "a") as f:
        f.write('{"v": 1, "kind": "round", "ro')
    evs = JsonlSink.read(p)
    assert [e["round"] for e in evs if e["kind"] == "round"] == [0, 1]
    # resume: the watermark suppresses already-streamed rounds
    t2 = Telemetry.to_jsonl(p, resume=True)
    assert t2.watermark == 2
    assert not t2.emit_round_bundle(1, engine="g", metrics={})
    assert t2.emit_round_bundle(2, engine="g", metrics={})
    t2.close()
    check_stream(JsonlSink.read(p))


def test_jsonl_repair_tail_on_resume(tmp_path):
    """A SIGKILL mid-bundle can leave (a) a truncated final line and
    (b) complete fault lines whose round event never landed.  Resuming
    must repair both: (a) would otherwise sit mid-file once appended
    events follow it, (b) would be silently double-counted when the
    resumed run re-emits the unfinished round's bundle."""
    p = tmp_path / "m.jsonl"
    t = Telemetry.to_jsonl(p)
    t.emit("run", engine="g", name="x", round=0)
    t.emit_round_bundle(0, engine="g", metrics={"a": 1.0})
    t.close()
    fault1 = {"round": 1, "worker": 2, "kind": "crash",
              "action": "skipped_round"}
    with open(p, "a") as f:
        # orphaned complete fault line of the unfinished round-1 bundle
        f.write(json.dumps(make_event("fault", round=1, worker=2,
                                      fault="crash",
                                      action="skipped_round")) + "\n")
        # then the torn round event itself
        f.write('{"v": 1, "kind": "round", "ro')
    t2 = Telemetry.to_jsonl(p, resume=True)
    assert t2.watermark == 1
    t2.emit_round_bundle(1, engine="g", metrics={"a": 0.5}, faults=[fault1])
    t2.close()
    merged = JsonlSink.read(p)      # raises if the torn line merged
    check_stream(merged)
    assert [e["round"] for e in merged if e["kind"] == "round"] == [0, 1]
    assert len([e for e in merged if e["kind"] == "fault"]) == 1


def test_jsonl_repair_heals_unterminated_final_event(tmp_path):
    """A kill can also tear the flush between an event's closing brace
    and its newline: the line parses (JSON self-delimits) so the round
    is complete — repair must HEAL the terminator, not drop the line,
    or the resume watermark (which counts the parseable line) would
    suppress a round the repaired file no longer carries."""
    p = tmp_path / "m.jsonl"
    t = Telemetry.to_jsonl(p)
    t.emit("run", engine="g", name="x", round=0)
    t.emit_round_bundle(0, engine="g", metrics={"a": 1.0},
                        faults=[{"round": 0, "worker": 1, "kind": "crash",
                                 "action": "skipped_round"}],
                        gauges={"g1": 2.0})
    t.emit_round_bundle(1, engine="g", metrics={"a": 0.5})
    t.close()
    raw = p.read_bytes()
    assert raw.endswith(b"\n")
    p.write_bytes(raw[:-1])
    t2 = Telemetry.to_jsonl(p, resume=True)
    assert t2.watermark == 2            # round 1 still counts
    t2.emit_round_bundle(2, engine="g", metrics={"a": 0.25})
    t2.close()
    merged = JsonlSink.read(p)
    check_stream(merged)
    assert [e["round"] for e in merged if e["kind"] == "round"] == [0, 1, 2]
    assert len([e for e in merged if e["kind"] == "fault"]) == 1


def test_memory_ring_capacity():
    mem = MemorySink(capacity=3)
    for i in range(10):
        mem.emit(make_event("gauge", round=i, name="x", value=float(i)))
    assert len(mem) == 3
    assert [e["round"] for e in mem.events] == [7, 8, 9]


def test_prometheus_snapshot(tmp_path):
    prom = PrometheusSink(tmp_path / "prom.txt")
    t = Telemetry([prom])
    t.emit_round_bundle(0, engine="f", metrics={"test_acc": 0.25},
                        faults=[{"round": 0, "worker": 1, "kind": "crash",
                                 "action": "x"},
                                {"round": 0, "worker": 2, "kind": "crash",
                                 "action": "x"}],
                        gauges={"stale_pending": 2.0})
    t.emit_round_bundle(1, engine="f", metrics={"test_acc": 0.75})
    t.close()
    text = (tmp_path / "prom.txt").read_text()
    # engine_kind rides as a LABEL (one family per signal, one series
    # per engine), with # HELP/# TYPE lines per family.
    assert 'dopt_round{engine_kind="f"} 1.0' in text
    assert 'dopt_test_acc{engine_kind="f"} 0.75' in text   # latest wins
    assert 'dopt_stale_pending{engine_kind="f"} 2.0' in text
    assert 'dopt_faults_total{kind="crash"} 2' in text
    assert "# HELP dopt_round" in text and "# TYPE dopt_round gauge" in text


def test_span_tracer_nesting_and_chrome_export(tmp_path):
    tr = SpanTracer()
    with tr.span("block"):
        with tr.span("eval"):
            pass
        with tr.span("checkpoint"):
            pass
    chrome = tr.to_chrome()
    assert [e["name"] for e in chrome] == ["block", "eval", "checkpoint"]
    outer = chrome[0]
    for inner in chrome[1:]:
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    p = tr.write_chrome(tmp_path / "trace.json")
    payload = json.loads(p.read_text())
    assert len(payload["traceEvents"]) == 3
    assert set(tr.totals()) == {"block", "eval", "checkpoint"}


def test_check_cli(tmp_path):
    from dopt.obs.check import main

    good = tmp_path / "good.jsonl"
    t = Telemetry.to_jsonl(good)
    t.emit("run", engine="g", name="x", round=0)
    t.emit_round_bundle(0, engine="g", metrics={"a": 1.0})
    t.close()
    assert main([str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text(good.read_text() + json.dumps(
        make_event("round", round=5, engine="g", metrics={})) + "\n")
    assert main([str(bad)]) == 1                 # round gap
    assert main([str(tmp_path / "absent.jsonl")]) == 1


# --------------------------------------------------------------- History
def test_history_merge_resumed_watermark():
    h = History("m")
    h.append(round=0, loss=1.0)
    h.append(round=1, loss=0.9)
    resumed = [{"round": 0, "loss": 1.0}, {"round": 1, "loss": 0.9},
               {"round": 2, "loss": 0.8}, {"round": 3, "loss": 0.7}]
    assert h.merge_resumed(resumed) == 2         # duplicates dropped
    assert [r["round"] for r in h.rows] == [0, 1, 2, 3]
    with pytest.raises(ValueError, match="round gap"):
        h.merge_resumed([{"round": 6, "loss": 0.1}])
    with pytest.raises(ValueError, match="without an int"):
        h.merge_resumed([{"loss": 0.1}])


def test_history_heterogeneous_csv_roundtrip(tmp_path):
    h = History("h")
    h.append(round=0, avg_train_loss=1.0, avg_test_acc=0.5)
    h.append(round=1, avg_train_loss=0.9)        # non-eval round
    h.append(round=2, avg_train_loss=0.8, extra_col=7)
    p = h.to_csv(tmp_path / "h.csv")
    header = p.read_text().splitlines()[0]
    assert header == ",round,avg_test_acc,avg_train_loss,extra_col"
    back = History.from_csv(p)
    # blanks are ABSENT keys again, not empty strings
    assert back.rows == h.rows


# ------------------------------------------------------------- profiling
def test_device_stats_degrade_returns_warning(monkeypatch):
    # The real profiler costs ~15s/capture on the 8-device CPU mesh;
    # the degrade contract is about what happens AROUND it, so stub
    # start/stop and fail the reduction (the realistic mid-bench mode:
    # xprof import/parse breakage).
    from dopt.utils import profiling

    monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                        lambda d: None)
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace", lambda: None)

    def boom(_):
        raise RuntimeError("no xprof here")

    monkeypatch.setattr(profiling, "xplane_op_stats", boom)
    mem = MemorySink()
    ran = []
    stats = profiling.device_stats_of(lambda: ran.append(1),
                                      telemetry=Telemetry([mem]))
    assert ran == [1]                            # the workload still ran
    assert "no xprof here" in stats["warning"]
    assert math.isnan(stats["device_self_time_us"])
    assert stats["device_phases"] == {}
    warns = [e for e in mem.events if e["kind"] == "warning"]
    assert warns and warns[0]["source"] == "device_stats_of"
    assert math.isnan(profiling.device_time_of(lambda: None))

    # profiler-start failure is its own degrade branch: no reduction is
    # attempted, fn still runs, the workload error contract holds
    def dead_start(_):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(profiling.jax.profiler, "start_trace", dead_start)
    stats = profiling.device_stats_of(lambda: None)
    assert "profiler busy" in stats["warning"]
    with pytest.raises(ZeroDivisionError):
        profiling.device_stats_of(lambda: 1 / 0)  # fn errors propagate


def test_phase_timers_tracer_hook():
    from dopt.utils.profiling import PhaseTimers

    tr = SpanTracer()
    timers = PhaseTimers(tracer=tr)
    with timers.phase("host_batch_plan"):
        pass
    timers.measure("round_step", lambda: 1)
    assert timers.counts["host_batch_plan"] == 1
    assert sorted(s["name"] for s in tr.spans) == ["host_batch_plan",
                                                   "round_step"]


# ------------------------------------------------- engine stream contracts
def test_federated_stream_blocked_equality_and_off_identity(fed_continuous):
    hc, stream = fed_continuous
    s = check_stream(stream)
    assert s["rounds"] == _ROUNDS
    assert s["kinds"]["fault"] == len(hc.faults)
    # typed fault events mirror the ledger row-for-row, in order
    assert [(e["round"], e["worker"], e["fault"], e["action"])
            for e in stream if e["kind"] == "fault"] == \
        [(r["round"], r["worker"], r["kind"], r["action"])
         for r in hc.faults]
    # the cocktail actually exercised the gauges it claims to carry
    names = {e["name"] for e in stream if e["kind"] == "gauge"}
    assert {"quarantine_active", "screen_streak_max", "stale_pending",
            "stale_weight_total", "consensus_distance"} <= names

    # telemetry OFF is the exact pre-change loop: same rows, same ledger
    plain = _trainer(_fed_cfg())
    hp = plain.run(rounds=_ROUNDS)
    assert hp.rows == hc.rows and hp.faults == hc.faults

    # blocked execution (fused chaos scan) emits the identical stream
    blk = _trainer(_fed_cfg())
    mem_b = MemorySink()
    attach(blk, Telemetry([mem_b]), fresh=True)
    hb = blk.run(rounds=_ROUNDS, block=3)
    assert hb.rows == hc.rows and hb.faults == hc.faults
    assert canonical(mem_b.events) == canonical(stream)


def test_gossip_stream_blocked_equality_and_off_identity(gossip_continuous):
    hc, stream = gossip_continuous
    s = check_stream(stream)
    assert s["rounds"] == _ROUNDS
    assert s["kinds"]["fault"] == len(hc.faults)
    names = {e["name"] for e in stream if e["kind"] == "gauge"}
    assert {"quarantine_active", "consensus_distance"} <= names

    plain = _trainer(_gossip_cfg())
    hp = plain.run(rounds=_ROUNDS)
    assert hp.rows == hc.rows and hp.faults == hc.faults

    blk = _trainer(_gossip_cfg())
    mem_b = MemorySink()
    attach(blk, Telemetry([mem_b]), fresh=True)
    hb = blk.run(rounds=_ROUNDS, block=3)
    assert hb.rows == hc.rows and hb.faults == hc.faults
    assert canonical(mem_b.events) == canonical(stream)


def test_kill_resume_stream_watermark(fed_continuous, tmp_path):
    hc, stream = fed_continuous
    mpath = tmp_path / "m.jsonl"
    ck = tmp_path / "ck"
    kill_at = _ROUNDS // 2
    part = _trainer(_fed_cfg())
    t1 = Telemetry.to_jsonl(mpath)
    attach(part, t1)
    part.run(rounds=kill_at, checkpoint_every=1, checkpoint_path=ck)
    t1.close()
    # the PhaseTimers tracer hook spans the existing timer sites,
    # checkpoint writes included, with zero run-loop changes
    span_names = {s["name"] for s in t1.tracer.spans}
    assert {"host_batch_plan", "round_step", "checkpoint"} <= span_names

    res = _trainer(_fed_cfg())
    res.restore(ck)
    t2 = Telemetry.to_jsonl(mpath, resume=True)
    assert t2.watermark == kill_at
    attach(res, t2)
    hk = res.run(rounds=_ROUNDS - res.round)
    t2.close()
    assert hk.rows == hc.rows and hk.faults == hc.faults

    merged = JsonlSink.read(mpath)
    check_stream(merged)
    # no duplicated or missing rounds across the kill
    assert [e["round"] for e in merged
            if e["kind"] == "round"] == list(range(_ROUNDS))
    assert (canonical(merged, kinds=("round", "fault"))
            == canonical(stream, kinds=("round", "fault")))

    # History.merge_resumed enforces the same watermark for row merges
    h = History("m")
    h.rows = [dict(r) for r in hc.rows[:kill_at]]
    assert h.merge_resumed(hk.rows) == _ROUNDS - kill_at
    assert h.rows == hc.rows


def test_attach_emits_segment_header(fed_continuous):
    _, stream = fed_continuous
    runs = [e for e in stream if e["kind"] == "run"]
    assert len(runs) == 1
    assert runs[0]["engine"] == "federated"
    assert runs[0]["workers"] == _DATA.num_users
    assert runs[0]["round"] == 0


def test_attach_header_uses_trainer_round():
    """Resuming a checkpointed trainer into a FRESH metrics file: the
    segment header must declare the trainer's actual starting round,
    not the (empty) file's watermark — the checker anchors round
    continuity on the header."""
    from dopt.utils.profiling import PhaseTimers

    class _Tr:
        round = 7
        engine_kind = "federated"
        num_workers = 4
        timers = PhaseTimers()

    mem = MemorySink()
    tele = attach(_Tr(), Telemetry([mem]))
    assert tele.watermark == 7
    tele.emit_round_bundle(7, engine="federated", metrics={"a": 1.0})
    check_stream(mem.events)
    assert [e["round"] for e in mem.events if e["kind"] == "run"] == [7]
