"""First-divergence stream differ: ``python -m dopt.obs.diff A B``.

The bit-identity assertion every soak re-implemented inline — "these
two telemetry streams are canonically equal" — as a reusable CLI with
a readable report.  Both streams are reduced to their canonical form
(events filtered to ``DETERMINISTIC_KINDS``, wall-clock ``ts``
dropped — exactly ``dopt.obs.canonical``) and compared element-wise;
on divergence the report names the FIRST differing canonical event:
its index, kind, round, and both payloads, which is what you actually
need to debug a replay drift (a wall of "streams differ" tells you
nothing; "gauge quarantine_active at round 17: 2.0 vs 3.0" tells you
where to look).

Exit codes follow the shared ``dopt.analysis`` convention: 0 streams
canonically identical, 1 divergent (or unreadable), 2 usage error;
``--json`` prints one machine-readable report.  ``--kinds`` narrows or
widens the compared kinds (``--kinds round,control``); ``--all-kinds``
compares every event including the non-deterministic channels (then
only ``ts`` is dropped — useful for comparing two copies of the SAME
file, not two executions).

Stdlib-only (no jax): diff streams on any laptop.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Iterable, Sequence

from dopt.obs.events import DETERMINISTIC_KINDS, KINDS, canonical
from dopt.obs.sinks import JsonlSink


def first_divergence(events_a: Iterable[dict], events_b: Iterable[dict],
                     kinds: Sequence[str] = DETERMINISTIC_KINDS,
                     ) -> dict[str, Any] | None:
    """Compare two event streams in canonical form; None when equal,
    else a report dict: the first differing canonical index, both
    events (None for the stream that ended early), kind and round of
    the surviving side, and a one-line ``reason``."""
    return diverge_canonical(canonical(events_a, kinds=tuple(kinds)),
                             canonical(events_b, kinds=tuple(kinds)))


def diverge_canonical(ca: list[dict], cb: list[dict],
                      ) -> dict[str, Any] | None:
    """The comparison core over ALREADY-canonicalized streams (callers
    that need the canonical lists anyway avoid building them twice)."""
    for i in range(min(len(ca), len(cb))):
        if ca[i] != cb[i]:
            return {"index": i, "a": ca[i], "b": cb[i],
                    "kind": ca[i].get("kind"),
                    "round": ca[i].get("round"),
                    "reason": "payload mismatch"}
    if len(ca) != len(cb):
        i = min(len(ca), len(cb))
        longer = ca if len(ca) > len(cb) else cb
        return {"index": i,
                "a": ca[i] if i < len(ca) else None,
                "b": cb[i] if i < len(cb) else None,
                "kind": longer[i].get("kind"),
                "round": longer[i].get("round"),
                "reason": (f"stream {'b' if len(cb) < len(ca) else 'a'} "
                           f"ends at canonical event {i} (other has "
                           f"{max(len(ca), len(cb))})")}
    return None


def format_divergence(path_a: str, path_b: str,
                      div: dict[str, Any]) -> str:
    def _show(ev: Any) -> str:
        return "<stream ended>" if ev is None else json.dumps(
            ev, sort_keys=True)

    return "\n".join([
        f"streams diverge at canonical event {div['index']} "
        f"(kind={div['kind']}, round={div['round']}): {div['reason']}",
        f"  a ({path_a}):",
        f"    {_show(div['a'])}",
        f"  b ({path_b}):",
        f"    {_show(div['b'])}",
    ])


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("a", metavar="METRICS_A")
    ap.add_argument("b", metavar="METRICS_B")
    ap.add_argument("--kinds", default=None, metavar="KIND[,KIND...]",
                    help="compare these event kinds (default: the "
                         f"deterministic kinds {DETERMINISTIC_KINDS})")
    ap.add_argument("--all-kinds", action="store_true",
                    help="compare every kind (only ts dropped) — for "
                         "comparing two copies of the same stream, not "
                         "two executions")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout (the "
                         "dopt.analysis CLI convention)")
    args = ap.parse_args(argv)

    kinds: Sequence[str] = DETERMINISTIC_KINDS
    if args.all_kinds:
        kinds = KINDS
    elif args.kinds:
        kinds = tuple(k.strip() for k in args.kinds.split(",") if k.strip())
        unknown = [k for k in kinds if k not in KINDS]
        if unknown:
            ap.error(f"unknown kinds {unknown} (want a subset of {KINDS})")

    try:
        ev_a = JsonlSink.read(args.a)
        ev_b = JsonlSink.read(args.b)
    except (OSError, ValueError) as e:
        if args.json:
            json.dump({"tool": "dopt.obs.diff", "identical": False,
                       "error": str(e)}, sys.stdout, indent=2,
                      sort_keys=True)
            sys.stdout.write("\n")
        else:
            print(f"FAIL {e}", file=sys.stderr)
        return 1

    ca = canonical(ev_a, kinds=tuple(kinds))
    cb = canonical(ev_b, kinds=tuple(kinds))
    div = diverge_canonical(ca, cb)
    n = len(ca)
    if args.json:
        json.dump({"tool": "dopt.obs.diff", "a": args.a, "b": args.b,
                   "kinds": list(kinds), "identical": div is None,
                   "canonical_events": n, "divergence": div},
                  sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    elif div is None:
        print(f"identical: {n} canonical events "
              f"(kinds {','.join(kinds)})")
    else:
        print(format_divergence(args.a, args.b, div), file=sys.stderr)
    return 0 if div is None else 1


if __name__ == "__main__":
    raise SystemExit(main())
