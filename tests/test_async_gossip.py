"""One-peer time-varying topology + asynchronous (staleness-1) gossip.

Engine-level contracts for ``GossipConfig.topology='one_peer_exp'`` and
``GossipConfig.mixing='async'``: blocked/prefetched/resumed execution is
bit-identical to the per-round trace (the canonical-stream guarantee
extended to both new modes), async round 0 coincides with sync round 0
(round −1's state is the shared init), faults ride the same stateless
draws, and the composition rules reject the layers a stale mix cannot
screen.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         GossipConfig, ModelConfig, OptimizerConfig,
                         RobustConfig)
from dopt.engine import GossipTrainer


def _cfg(faults=None, iid=True, robust=None, population=None, **g_over):
    g = dict(algorithm="dsgd", topology="one_peer_exp", mode="metropolis",
             rounds=4, local_ep=1, local_bs=32)
    g.update(g_over)
    return ExperimentConfig(
        name="t", seed=7,
        data=DataConfig(dataset="synthetic", num_users=8, iid=iid, shards=2,
                        synthetic_train_size=512, synthetic_test_size=128),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        faults=faults or FaultConfig(),
        robust=robust,
        population=population,
        gossip=GossipConfig(**g))


def _fetch(tr):
    return jax.tree.map(np.asarray, jax.device_get(tr.params))


def _same(a, b):
    return all(np.array_equal(x, y)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_one_peer_exp_sync_blocked_parity_and_learns(devices):
    tr = GossipTrainer(_cfg())
    # n=8: the compiled shift set is the exponential-graph union
    # {2^0, 2^1, 2^2} plus shift 0 (diagonal + dropout-repair identity).
    assert tuple(tr._shift_ids) == (0, 1, 2, 4)
    h = tr.run(rounds=4, block=1)
    tr2 = GossipTrainer(_cfg())
    h2 = tr2.run(rounds=4, block=2)
    assert _same(_fetch(tr), _fetch(tr2)), \
        "one_peer_exp blocked execution diverged from per-round"
    assert h.rows == h2.rows
    accs = [r["avg_test_acc"] for r in h.rows if "avg_test_acc" in r]
    assert accs[-1] > accs[0], accs


def test_async_per_round_blocked_prefetched_parity(devices):
    tr1 = GossipTrainer(_cfg(mixing="async"))
    h1 = tr1.run(rounds=4, block=1)
    tr2 = GossipTrainer(_cfg(mixing="async"))
    h2 = tr2.run(rounds=4, block=2)
    tr3 = GossipTrainer(_cfg(mixing="async", prefetch="on"))
    h3 = tr3.run(rounds=4, block=2)
    p1, p2, p3 = _fetch(tr1), _fetch(tr2), _fetch(tr3)
    assert _same(p1, p2), "async blocked diverged from per-round"
    assert _same(p1, p3), "async prefetched-blocked diverged from per-round"
    assert h1.rows == h2.rows == h3.rows


def test_async_round0_equals_sync_round0(devices):
    # Round −1's state is defined as the shared init, so the stale read
    # of round 0 sees exactly what the sync mix sees.
    ts = GossipTrainer(_cfg())
    ts.run(rounds=1)
    ta = GossipTrainer(_cfg(mixing="async"))
    ta.run(rounds=1)
    assert _same(_fetch(ts), _fetch(ta))


def test_async_dense_path(devices):
    # comm_impl falls back to the dense all_gather contraction when the
    # topology has no usable shift union; the diag/off-diag split must
    # ride it too.
    tr = GossipTrainer(_cfg(topology="complete", mixing="async"))
    assert tr._shift_ids is None
    h = tr.run(rounds=2, block=2)
    assert len(h.rows) == 2


def test_async_resume_bit_exact(devices, tmp_path):
    ck = os.path.join(tmp_path, "ck")
    cont = GossipTrainer(_cfg(mixing="async"))
    cont.run(rounds=4, block=2)
    part = GossipTrainer(_cfg(mixing="async"))
    part.run(rounds=2, block=2, checkpoint_every=2, checkpoint_path=ck)
    res = GossipTrainer(_cfg(mixing="async"))
    res.restore(ck)
    assert res.round == 2
    res.run(rounds=2, block=2)
    assert _same(_fetch(cont), _fetch(res)), \
        "async killed-and-resumed run diverged from continuous"
    assert cont.history.rows == res.history.rows


def test_async_restore_requires_prev_buffer(devices, tmp_path):
    # A sync checkpoint has no staleness-1 buffer; resuming it async
    # would mix round t against the wrong previous-round snapshot.
    ck = os.path.join(tmp_path, "ck")
    sync = GossipTrainer(_cfg())
    sync.run(rounds=2, checkpoint_every=2, checkpoint_path=ck)
    res = GossipTrainer(_cfg(mixing="async"))
    with pytest.raises(ValueError, match="async_prev"):
        res.restore(ck)


def test_async_faults_blocked_parity(devices):
    # Crash + churn compose with async (the repaired identity row splits
    # into diag=1/off-diag=0 — a pure local step); the fused scan must
    # replay the identical storm AND ledger.
    fc = FaultConfig(crash=0.15, churn=0.1, churn_span=2)
    t1 = GossipTrainer(_cfg(faults=fc, mixing="async"))
    t1.run(rounds=4, block=1)
    t2 = GossipTrainer(_cfg(faults=fc, mixing="async"))
    t2.run(rounds=4, block=2)
    assert _same(_fetch(t1), _fetch(t2))
    assert t1.history.faults == t2.history.faults
    assert t1.history.faults, "cocktail drew no faults — raise the rates"


def test_one_peer_exp_consensus_contracts(devices):
    # The schedule's per-period product is exact uniform averaging, so
    # non-IID workers end closer together than under no consensus.
    tr = GossipTrainer(_cfg(iid=False))
    tr.run(rounds=4)
    spread = max(float(np.std(np.asarray(l), axis=0).max())
                 for l in jax.tree.leaves(tr.params))
    tr2 = GossipTrainer(_cfg(iid=False, topology="circle",
                             algorithm="nocons"))
    tr2.run(rounds=4)
    spread_no = max(float(np.std(np.asarray(l), axis=0).max())
                    for l in jax.tree.leaves(tr2.params))
    assert spread < spread_no


def test_one_peer_exp_non_power_of_two_rejected(devices):
    cfg = dataclasses.replace(
        _cfg(), data=dataclasses.replace(_cfg().data, num_users=6))
    with pytest.raises(ValueError, match="power-of-2"):
        GossipTrainer(cfg)


@pytest.mark.parametrize("over, match", [
    (dict(mixing="asink"), "unknown gossip mixing"),
    (dict(mixing="async", algorithm="fedlcon", eps=2), "single-sweep"),
    (dict(mixing="async", correction="push_sum"), "link faults"),
    (dict(mixing="async", update_sharding="scatter"), "scatter"),
])
def test_async_composition_rejections(devices, over, match):
    with pytest.raises(ValueError, match=match):
        GossipTrainer(_cfg(**over))


def test_async_rejects_link_faults_and_robust(devices):
    with pytest.raises(ValueError, match="link faults"):
        GossipTrainer(_cfg(mixing="async",
                           faults=FaultConfig(msg_drop=0.2)))
    with pytest.raises(ValueError, match="robust"):
        GossipTrainer(_cfg(mixing="async",
                           robust=RobustConfig(clip_radius=1.0)))
