"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence axis anywhere (SURVEY
§2.3: 2-layer CNNs on 28×28/32×32 images), so nothing here is owed for
parity — this is the framework's long-context substrate, built the TPU
way so models with a sequence dimension scale past one chip's HBM:

* ``ring_attention`` — blockwise-softmax attention with the KV shards
  rotating around the device ring via ``lax.ppermute`` (one hop per
  step, ICI neighbor traffic only).  Each device holds Q/K/V blocks of
  [B, L/D, H, Dh]; the running (max, numerator, denominator)
  flash-attention accumulators make the result exact, not approximate.
  Memory per device is O(L/D · L/D) per block pair instead of O(L²).
* ``ulysses_attention`` — the all-to-all alternative: reshard from
  sequence-sharded to head-sharded with ``all_to_all``, run exact
  attention locally over the full sequence for this device's head
  group, then reshard back.  One collective round-trip; the right
  choice when heads ≥ devices and full-sequence attention fits.

Both are pure ``shard_map`` programs over a 1-D mesh axis and are
verified elementwise against single-device dense attention in
``tests/test_sequence.py`` on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dopt.parallel.mesh import compat_shard_map

SEQ_AXIS = "sp"


def _block_attn(q, k, v, *, scale, mask=None):
    """Unnormalised blockwise attention: returns (numerator [B,Lq,H,Dh],
    denominator [B,Lq,H], rowmax [B,Lq,H]) for one KV block."""
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale  # [B, Lq, H, Lk]
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # [B, Lq, H]
    # All-masked rows (causal block fully in the future) produce -inf
    # rowmax; zero them so exp() never sees NaN and they contribute 0.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    num = jnp.einsum("bqhk,bkhd->bqhd", p, v)
    den = p.sum(axis=-1)
    return num, den, m_safe


def _combine(num1, den1, m1, num2, den2, m2):
    """Merge two blockwise-softmax partial results (flash combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    num = num1 * a1[..., None] + num2 * a2[..., None]
    den = den1 * a1 + den2 * a2
    return num, den, m


@jax.checkpoint
def _block_attn_remat(q, k, v, scale, mask):
    """``_block_attn`` under rematerialisation.  Used inside the ring /
    KV-chunk scans: without remat, autodiff saves every iteration's
    [B, Lq, H, Lk] score matrix as a scan residual, so the backward pass
    holds O(L²) no matter how small the chunks are — the whole point of
    blockwise attention evaporates.  Remat recomputes the scores from
    (q, k, v) in the backward (the standard flash-attention trade:
    ~⅓ more attention FLOPs for O(block·chunk) peak memory)."""
    return _block_attn(q, k, v, scale=scale, mask=mask)


def dense_attention(q, k, v, *, causal: bool = False):
    """Single-device exact attention — the correctness reference.
    q, k, v: [B, L, H, Dh]."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        lq, lk = s.shape[1], s.shape[3]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)


def _block_attn_chunked(qb, kb_t, vb_t, *, scale, q_pos, k_pos0, chunk):
    """Blockwise attention against one KV block, itself scanned in
    ``chunk``-sized KV slices (flash-style): peak score memory drops
    from O(Lq·Lk) to O(Lq·chunk) per device without changing the exact
    result — the running (num, den, max) accumulators combine chunks
    the same way ring steps combine blocks.  ``q_pos``/``k_pos0`` are
    global positions for exact cross-chunk causal masking (pass
    ``q_pos=None`` for non-causal)."""
    lk = kb_t.shape[1]
    nchunks = lk // chunk
    kc = kb_t.reshape(kb_t.shape[0], nchunks, chunk, *kb_t.shape[2:])
    vc = vb_t.reshape(vb_t.shape[0], nchunks, chunk, *vb_t.shape[2:])

    def chunk_step(carry, ci):
        num, den, m = carry
        kb_c = jax.lax.dynamic_index_in_dim(kc, ci, axis=1, keepdims=False)
        vb_c = jax.lax.dynamic_index_in_dim(vc, ci, axis=1, keepdims=False)
        if q_pos is not None:
            k_pos = k_pos0 + ci * chunk + jnp.arange(chunk)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, :, None, :]
        else:
            mask = None
        num2, den2, m2 = _block_attn_remat(qb, kb_c, vb_c, scale, mask)
        return _combine(num, den, m, num2, den2, m2), None

    num0 = qb * 0
    den0 = jnp.sum(num0, axis=-1)
    m0 = den0 - jnp.inf
    (num, den, m), _ = jax.lax.scan(chunk_step, (num0, den0, m0),
                                    jnp.arange(nchunks))
    return num, den, m


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                   axis: str = SEQ_AXIS, kv_chunk: int | None = None):
    """Exact attention with the sequence axis sharded over ``mesh``.

    q, k, v: [B, L, H, Dh] global-view arrays (L divisible by the mesh
    size).  Device d starts with block d and receives block
    (d+1), (d+2), ... as the KV pair rotates around the ring — D-1
    ``ppermute`` hops, each overlapping the local blockwise attention.
    Causal masking is exact across blocks: query block i attends to key
    block j at full, diagonal, or zero visibility depending on i vs j.

    ``kv_chunk`` additionally scans each block's KV in chunks of that
    size (must divide the block), bounding per-device score memory at
    O(block · kv_chunk) instead of O(block²) — the knob that takes one
    device's block past what a materialised attention matrix allows.
    """
    n = mesh.shape[axis]
    l = q.shape[1]
    if l % n:
        raise ValueError(f"sequence length {l} not divisible by mesh axis {n}")
    block = l // n
    if kv_chunk is not None and (kv_chunk <= 0 or block % kv_chunk):
        raise ValueError(f"kv_chunk {kv_chunk} must divide the per-device "
                         f"block {block}")
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)

    def local(qb, kb, vb):
        # qb/kb/vb: [B, block, H, Dh] — this device's shard.
        my = jax.lax.axis_index(axis)          # query-block index
        q_pos = my * block + jnp.arange(block)  # global query positions

        def step(carry, t):
            kv, num, den, m = carry
            kb_t, vb_t = kv
            kv_idx = (my + t) % n               # which key block we hold now
            if kv_chunk is not None:
                num2, den2, m2 = _block_attn_chunked(
                    qb, kb_t, vb_t, scale=scale,
                    q_pos=q_pos if causal else None,
                    k_pos0=kv_idx * block, chunk=kv_chunk)
            else:
                if causal:
                    k_pos = kv_idx * block + jnp.arange(block)
                    mask = q_pos[:, None] >= k_pos[None, :]  # [block, block]
                    mask = mask[None, :, None, :]            # [1, Lq, 1, Lk]
                else:
                    mask = None
                num2, den2, m2 = _block_attn_remat(qb, kb_t, vb_t, scale,
                                                   mask)
            num, den, m = _combine(num, den, m, num2, den2, m2)

            # Rotate KV to the next device — except after the last
            # block, whose rotation would be discarded with the carry
            # (saves one redundant KV-pair hop per call).
            def rotate(kv):
                perm = [((d + 1) % n, d) for d in range(n)]
                return (jax.lax.ppermute(kv[0], axis, perm),
                        jax.lax.ppermute(kv[1], axis, perm))

            kb_n, vb_n = jax.lax.cond(t < n - 1, rotate,
                                      lambda kv: kv, (kb_t, vb_t))
            return ((kb_n, vb_n), num, den, m), None

        # Derive the accumulators from qb so they carry the same
        # varying-manual-axes type as the scan outputs (shard_map
        # rejects unvarying-constant carries combined with varying
        # results).
        num0 = qb * 0
        den0 = jnp.sum(num0, axis=-1)
        m0 = den0 - jnp.inf
        (_, num, den, m), _ = jax.lax.scan(
            step, ((kb, vb), num0, den0, m0), jnp.arange(n))
        # Fully-masked rows (never happens for causal self-attention,
        # where every query sees at least itself) would have den 0.
        return num / jnp.maximum(den, 1e-30)[..., None]

    spec = P(None, axis, None, None)
    fn = compat_shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                      axis: str = SEQ_AXIS):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    Input is sequence-sharded [B, L/D, H, Dh] per device; one
    ``all_to_all`` turns it head-sharded [B, L, H/D, Dh], local exact
    attention runs over the FULL sequence for this device's heads, and
    a second ``all_to_all`` restores sequence sharding.  Requires the
    head count divisible by the mesh axis size.
    """
    n = mesh.shape[axis]
    h = q.shape[2]
    if h % n:
        raise ValueError(f"num heads {h} not divisible by mesh axis {n}")
    if q.shape[1] % n:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by {n}")

    def local(qb, kb, vb):
        def seq_to_heads(x):
            # [B, L/D, H, Dh] -> [B, L, H/D, Dh]
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qh, kh, vh = seq_to_heads(qb), seq_to_heads(kb), seq_to_heads(vb)
        out = dense_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(out)

    spec = P(None, axis, None, None)
    fn = compat_shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)


def make_seq_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the sequence-parallel axis."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    return Mesh(devs[:n], (SEQ_AXIS,))
