"""Pallas TPU kernels for the bandwidth-bound hot op: the SGD update.

The per-step parameter update (torch semantics, ``dopt.optim.sgd_step``)

    buf ← μ·buf + g ;  p ← p − lr·buf

reads three arrays and writes two with zero FLOP reuse — pure HBM
bandwidth.  This kernel pins the fusion into ONE pass over memory
(in-place via ``input_output_aliases``) instead of trusting XLA's fusion
heuristics, and is the template for further pallas work (quantised
gossip payloads, ring-reduce mixing).

Numerics match the jnp path to fused-multiply-add association (the same
fp32 ops in the same order; only FMA contraction may differ between the
two compiled programs — ``tests/test_ops.py`` asserts 1e-6 agreement),
so the fast path stays oracle-comparable.

Layout: each leaf is viewed as a padded [rows, 128] fp32 tile grid
(lane = 128, sublane multiple of 8 — the fp32 VMEM tile), gridded over
row blocks.  On non-TPU backends the kernel runs in interpret mode, so
CPU tests exercise the identical code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_SUBLANE = 8
_BLOCK_ROWS = 512  # 512×128 fp32 = 256 KiB per operand block in VMEM


def pallas_available() -> bool:
    """True when a real TPU backend is present (compiled kernels);
    otherwise callers fall back to interpret mode or pure jnp."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend probing
        return False


def _make_kernel(lr: float, mu: float):
    def kernel(p_ref, m_ref, g_ref, p_out, m_out):
        buf = mu * m_ref[:] + g_ref[:]
        m_out[:] = buf
        p_out[:] = p_ref[:] - lr * buf

    return kernel


@partial(jax.jit, static_argnames=("lr", "mu", "interpret"))
def fused_sgd_momentum(p, m, g, *, lr: float, mu: float,
                       interpret: bool = False):
    """Fused momentum-SGD update of ONE array (any shape/dtype).

    Returns (new_p, new_buf) with p's shape/dtype, computed in fp32
    exactly like ``sgd_step``'s two tree.maps but in a single memory
    pass.
    """
    shape, dtype = p.shape, p.dtype
    n = p.size
    rows = -(-n // _LANE)
    if rows <= _BLOCK_ROWS:
        rows_pad = -(-rows // _SUBLANE) * _SUBLANE
        grid = 1
        block_rows = rows_pad
    else:
        rows_pad = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
        grid = rows_pad // _BLOCK_ROWS
        block_rows = _BLOCK_ROWS

    def tile(x):
        x = x.astype(jnp.float32).reshape(-1)
        return jnp.pad(x, (0, rows_pad * _LANE - n)).reshape(rows_pad, _LANE)

    pt, mt, gt = tile(p), tile(m), tile(g)
    spec = pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    new_p, new_m = pl.pallas_call(
        _make_kernel(float(lr), float(mu)),
        out_shape=(jax.ShapeDtypeStruct(pt.shape, jnp.float32),
                   jax.ShapeDtypeStruct(mt.shape, jnp.float32)),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(pt, mt, gt)

    def untile(x):
        return x.reshape(-1)[:n].reshape(shape).astype(dtype)

    return untile(new_p), untile(new_m)


def fused_sgd_momentum_tree(params, momentum, grads, *, lr: float, mu: float,
                            interpret: bool | None = None):
    """Tree-map the fused kernel over a params pytree.

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (same code path, testable on CPU).
    """
    if interpret is None:
        interpret = not pallas_available()
    new_p, new_m = [], []
    p_leaves, treedef = jax.tree.flatten(params)
    m_leaves = treedef.flatten_up_to(momentum)
    g_leaves = treedef.flatten_up_to(grads)
    # dopt_update scope: phase attribution for the profiler's
    # conv/comm/update split (dopt.utils.profiling.classify_phase).
    with jax.named_scope("dopt_update"):
        for p, m, g in zip(p_leaves, m_leaves, g_leaves):
            np_, nm_ = fused_sgd_momentum(p, m, g, lr=lr, mu=mu,
                                          interpret=interpret)
            new_p.append(np_)
            new_m.append(nm_)
    return treedef.unflatten(new_p), treedef.unflatten(new_m)
