from dopt.parallel.mesh import make_mesh, shard_worker_tree, worker_sharding
from dopt.parallel.collectives import masked_average, mix_dense, mix_shifts_shardmap

__all__ = [
    "make_mesh",
    "shard_worker_tree",
    "worker_sharding",
    "masked_average",
    "mix_dense",
    "mix_shifts_shardmap",
]
