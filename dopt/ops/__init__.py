from dopt.ops.fused_update import (
    fused_sgd_momentum,
    fused_sgd_momentum_tree,
    pallas_available,
)

__all__ = [
    "fused_sgd_momentum",
    "fused_sgd_momentum_tree",
    "pallas_available",
]
