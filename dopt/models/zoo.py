"""Model zoo in flax.linen (TPU compute path).

Re-creates the reference's two CNNs with exact parameter-count parity
(``models.py`` in both reference projects — `Model1`: 1,663,370 params
for MNIST/FMNIST, `Model3`: 1,105,098 for CIFAR-10) and adds the models
the benchmark configs need: an MLP, ℓ2-regularised logistic regression
(a9a / ADMM), and a GroupNorm ResNet-18 for the 32-worker CIFAR-10
north-star config.

Faithful-head semantics: the reference ends both CNNs in ``nn.Softmax``
*and* trains with ``CrossEntropyLoss`` (which applies log_softmax
internally) — a double softmax (SURVEY §3.4).  ``faithful=True``
reproduces that: ``__call__`` returns *probabilities* and the loss in
``dopt.models.losses`` applies log_softmax on top, bit-matching the
reference's objective.  ``faithful=False`` returns logits (the
corrected, idiomatic head).

Data layout is NHWC (TPU-native).  The reference flattens NCHW
channel-major before its first Dense layer; parameter-conversion
helpers in ``dopt.engine.oracle`` handle that reordering so torch and
flax foward passes are comparable element-wise.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _head(x: jnp.ndarray, faithful: bool) -> jnp.ndarray:
    """Output head: softmax probabilities in faithful mode (the
    reference's double-softmax objective), logits otherwise."""
    if faithful:
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)
    return x


@jax.custom_vjp
def _tiled_max(x6: jnp.ndarray) -> jnp.ndarray:
    """max over the window axes (2, 4) of a [b, h2, 2, w2, 2, c] tiling
    with a FIRST-WINNER backward: the gradient goes to the first window
    element attaining the max in (di, dj) row-major order — torch
    ``MaxPool2d``'s tie semantics (its backward routes through the
    argmax index, first occurrence in kernel scan order) — instead of
    jax's equal split across ties.  Ties are NOT measure-zero in
    practice: the faithful Model1 conv has no ReLU, so zero-background
    MNIST pixels produce exact 4-way bias ties in every background
    window (ADVICE r4)."""
    return x6.max(axis=(2, 4))


def _tiled_max_fwd(x6):
    m = x6.max(axis=(2, 4))
    return m, (x6, m)


def _tiled_max_bwd(res, g):
    x6, m = res
    # First-winner in torch scan order (di, dj): (0,0),(0,1),(1,0),(1,1)
    # as a boolean cascade over the four window slices — pure
    # elementwise masking, no extra strided reduction, measured at
    # parity with jax's default equal-split backward and ~25% cheaper
    # than an argmin-index formulation on v5e.
    e = [x6[:, :, i, :, j, :] == m for i in (0, 1) for j in (0, 1)]
    seen = e[0]
    masks = [e[0]]
    for k in (1, 2, 3):
        masks.append(e[k] & ~seen)
        seen = seen | e[k]
    gm = [g * mk.astype(g.dtype) for mk in masks]
    return (jnp.stack([jnp.stack([gm[0], gm[1]], axis=3),
                       jnp.stack([gm[2], gm[3]], axis=3)], axis=2),)


_tiled_max.defvjp(_tiled_max_fwd, _tiled_max_bwd)


def _max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """2×2 stride-2 max pool via reshape + tiled reduce_max.

    Forward-identical to ``nn.max_pool(x, (2, 2), strides=(2, 2))`` for
    even H/W (the windows are non-overlapping, so the reshape tiles them
    exactly), but its VJP lowers to an elementwise first-winner mask
    instead of XLA's ``select_and_scatter`` — which the reduce_window
    backward otherwise costs us ~12% of device time on the Model1
    training step (results/trace_headline.json).  The custom VJP
    (``_tiled_max``) routes tie gradients to the FIRST window element in
    torch's kernel scan order, bit-matching MaxPool2d's backward even on
    real data with exact ties (e.g. zero-background MNIST under the
    no-ReLU faithful conv) — not jax's default equal split.

    Odd spatial dims fall back to ``nn.max_pool`` (which floors), since
    the reshape tiling requires even H/W.
    """
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        return nn.max_pool(x, (2, 2), strides=(2, 2))
    return _tiled_max(x.reshape(b, h // 2, 2, w // 2, 2, c))


class _ReferenceCNN(nn.Module):
    """Shared body of the reference's two CNNs (``models.py`` both
    projects): conv(·→32,k5,SAME) → maxpool2 → conv(32→64,k5,SAME) →
    maxpool2 → Dense(hidden) → ReLU → Dense(num_classes) [→ Softmax].
    They differ only in the first Dense width.

    Faithful quirk: the reference conv stack has NO activations — the
    only ReLU sits between the two Dense layers (models.py:10-21).  Two
    stacked linear convs are a strictly weaker function class, but that
    is the architecture the published numbers used; ``faithful=True``
    reproduces it exactly, ``faithful=False`` adds the conventional
    post-conv ReLUs (and drops the softmax head)."""

    hidden: int = 512
    num_classes: int = 10
    faithful: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        if not self.faithful:
            x = nn.relu(x)
        x = _max_pool_2x2(x)
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        if not self.faithful:
            x = nn.relu(x)
        x = _max_pool_2x2(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        # Corrected head: compute the logits layer in f32 (standard
        # mixed-precision practice — the raw-logit objective is
        # sensitive to bf16 rounding of the logit gradients, measured
        # run-to-run final-acc scatter 0.3-0.96 vs a tight band with the
        # f32 head; ~5k MACs/sample, free).  Faithful mode keeps the
        # compute dtype end-to-end: its double-softmax objective is
        # insensitive (softmax squashing) and the oracle parity
        # contract pins its op sequence.
        head_dtype = self.dtype if self.faithful else jnp.float32
        x = nn.Dense(self.num_classes, dtype=head_dtype, name="fc2")(x)
        return _head(x, self.faithful)


class Model1(_ReferenceCNN):
    """MNIST/FMNIST CNN (reference ``models.py:6-27``), 1,663,370 params."""

    hidden: int = 512


class Model3(_ReferenceCNN):
    """CIFAR CNN (reference ``models.py:31-51``), 1,105,098 params @ 10 classes."""

    hidden: int = 256


class MLP(nn.Module):
    """Small MLP (BASELINE.json config 1: 4-worker MNIST MLP)."""

    hidden: Sequence[int] = (200, 200)
    num_classes: int = 10
    faithful: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, dtype=self.dtype, name=f"fc{i+1}")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return _head(x, self.faithful)


class LogisticRegression(nn.Module):
    """ℓ2-regularised logistic regression (BASELINE.json config 4:
    16-worker ADMM on a9a).  The ℓ2 term lives in the loss, not here."""

    num_classes: int = 2
    faithful: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="linear")(x)
        return _head(x, self.faithful)


class ResidualBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.features))(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    """CIFAR-style ResNet-18 with GroupNorm (BASELINE.json config 5:
    32-worker gossip SGD, CIFAR-10, time-varying random graphs).

    GroupNorm instead of BatchNorm: batch statistics are ill-defined
    under federated/gossip averaging (each worker's running stats
    diverge and averaging them is not principled), and GN keeps the
    model a pure function of (params, batch) — no mutable state to
    thread through the stacked-worker engine.  Standard choice in the
    FL literature.
    """

    num_classes: int = 10
    faithful: bool = False
    dtype: Any = jnp.float32
    stage_sizes: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=32)(x)
        x = nn.relu(x)
        for stage, blocks in enumerate(self.stage_sizes):
            features = 64 * (2 ** stage)
            for b in range(blocks):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = ResidualBlock(features, strides=strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return _head(x, self.faithful)


class TransformerLM(nn.Module):
    """Decoder-only transformer LM — the long-context member of the zoo.

    Nothing like it exists in the reference (no attention, no sequence
    axis anywhere — SURVEY §2.3); this is the framework's own
    demonstration that its sequence-parallel substrate
    (``dopt.parallel.sequence``) plugs into a real model.  ``attn_fn``
    injects the attention implementation: ``None`` uses single-device
    dense attention; pass ``lambda q,k,v: ring_attention(q,k,v,mesh,
    causal=True)`` (or the Ulysses variant) to shard the sequence axis
    over a mesh with NO other change to the model.

    Pre-LN blocks, learned positional embeddings, weight-tied output
    head.  Call input: [B, L] int32 tokens; output [B, L, vocab]
    logits (``num_classes`` is the vocab size).
    """

    num_classes: int = 256          # vocab
    faithful: bool = False          # kept for zoo-interface uniformity
    dtype: Any = jnp.float32
    dim: int = 128
    depth: int = 2
    heads: int = 4
    max_len: int = 2048

    @nn.compact
    def __call__(self, tokens, attn_fn=None):
        from dopt.parallel.sequence import dense_attention

        attn = attn_fn or (lambda q, k, v: dense_attention(q, k, v,
                                                           causal=True))
        b, l = tokens.shape
        if l > self.max_len:
            raise ValueError(f"sequence length {l} > max_len {self.max_len}")
        if self.dim % self.heads:
            raise ValueError(f"dim {self.dim} not divisible by "
                             f"heads {self.heads}")
        emb = nn.Embed(self.num_classes, self.dim, dtype=self.dtype,
                       name="tok_emb")
        x = emb(tokens)
        x = x + self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (self.max_len, self.dim))[None, :l].astype(self.dtype)
        hd = self.dim // self.heads
        for i in range(self.depth):
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln1_{i}")(x)
            qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                           name=f"qkv_{i}")(y)
            q, k, v = jnp.split(qkv.reshape(b, l, 3 * self.heads, hd), 3,
                                axis=2)
            o = attn(q, k, v).reshape(b, l, self.dim)
            x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                             name=f"proj_{i}")(o)
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln2_{i}")(x)
            y = nn.Dense(4 * self.dim, dtype=self.dtype, name=f"up_{i}")(y)
            y = nn.gelu(y)
            x = x + nn.Dense(self.dim, dtype=self.dtype, name=f"down_{i}")(y)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = x @ emb.embedding.T.astype(self.dtype)
        return _head(logits, self.faithful)


def _to_grouped_kernel(k):
    """[W, kh, kw, Cin, Cout] stacked conv kernel → the grouped-conv
    layout [kh, kw, Cin, W·Cout] (worker-major output channels).  A
    pure permutation — bit-exactly invertible."""
    g = jnp.moveaxis(k, 0, 3)
    return g.reshape(*g.shape[:3], -1)


def _conv_fast(z, g_kernel, groups, *, dtype, strides=(1, 1),
               padding="SAME", bias=None):
    """Worker-grouped conv on [B, H, Wd, G·Cin] with a pre-grouped
    [kh, kw, Cin, G·Cout] kernel (``_to_grouped_kernel`` layout)."""
    out = jax.lax.conv_general_dilated(
        z, g_kernel.astype(dtype), strides, padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)
    if bias is not None:
        out = out + bias.astype(dtype).reshape(1, 1, 1, -1)
    return out


def _group_norm_stacked(z, scale, bias, *, num_workers, groups_per_worker,
                        eps=1e-6):
    """flax ``GroupNorm`` over worker-major stacked channels.

    z is [B, H, Wd, W·C]; with worker-major channel packing the W·g
    stacked groups tile exactly into per-worker channel blocks, so each
    group's statistics are computed within one worker — identical math
    to vmapping GroupNorm(num_groups=g) per worker.

    Statistics use float32 ACCUMULATION (``jnp.mean(..., dtype=f32)``
    with flax's E[x²]−E[x]² formula) but the big activation tensor is
    never materialised in f32: the normalisation collapses to one fused
    ``z·a + c`` in the compute dtype with per-(sample, channel) f32
    coefficients — an explicit f32 upcast of the activations here cost
    41% of baseline5's device time as convert_element_type ops.
    """
    b, h, wd, wc = z.shape
    g = num_workers * groups_per_worker
    cpg = wc // g
    zg = z.reshape(b, h, wd, g, cpg)
    mean = jnp.mean(zg, axis=(1, 2, 4), dtype=jnp.float32)          # [b, g]
    mean2 = jnp.mean(jnp.square(zg), axis=(1, 2, 4), dtype=jnp.float32)
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)                                   # [b, g]
    inv_c = jnp.broadcast_to(inv[:, :, None], (b, g, cpg)).reshape(b, wc)
    mean_c = jnp.broadcast_to(mean[:, :, None], (b, g, cpg)).reshape(b, wc)
    sc = scale.reshape(wc).astype(jnp.float32)[None]
    bi = bias.reshape(wc).astype(jnp.float32)[None]
    a = (sc * inv_c).astype(z.dtype)
    c0 = (bi - mean_c * inv_c * sc).astype(z.dtype)
    return z * a[:, None, None, :] + c0[:, None, None, :]


def _map_named_kernels(tree, ndim, fn):
    """Recursively apply ``fn`` to every dict value under key 'kernel'
    whose rank is ``ndim``; everything else passes through."""
    if isinstance(tree, dict):
        return {k: (fn(v) if k == "kernel" and getattr(v, "ndim", 0) == ndim
                    else _map_named_kernels(v, ndim, fn))
                for k, v in tree.items()}
    return tree


def _make_stacked_resnet_apply(model: "ResNet18"):
    """Grouped-stacked forward for the GroupNorm ResNet-18 (the
    north-star config's model): every conv becomes a
    feature_group_count=W conv over worker-major channels, GroupNorm
    becomes W·32 stacked groups, and the head a batched einsum.

    The conv kernels are permuted into the grouped layout
    (``_to_grouped_kernel``) at the top of each apply; hoisting that
    relayout out of the step by CARRYING grouped-layout params through
    the scan was measured and rejected — XLA then picks worse layouts
    for the carried kernels (headline 378→401 ms/round, baseline5
    2410→2572 ms/round device time).
    """
    dtype, faithful = model.dtype, model.faithful
    stage_sizes = tuple(model.stage_sizes)

    def apply(params, x):
        fp = _map_named_kernels(params, 5, _to_grouped_kernel)
        w, b = x.shape[0], x.shape[1]
        z = jnp.moveaxis(x.astype(dtype), 0, 3)
        z = z.reshape(*z.shape[:3], -1)
        z = _conv_fast(z, fp["Conv_0"]["kernel"], w, dtype=dtype)
        gn = fp["GroupNorm_0"]
        z = _group_norm_stacked(z, gn["scale"], gn["bias"], num_workers=w,
                                groups_per_worker=32)
        z = nn.relu(z)
        blk = 0
        for stage, blocks in enumerate(stage_sizes):
            for bi in range(blocks):
                strides = 2 if (stage > 0 and bi == 0) else 1
                bp = fp[f"ResidualBlock_{blk}"]
                blk += 1
                gpw = min(32, bp["Conv_0"]["kernel"].shape[-1] // w)
                residual = z
                y = _conv_fast(z, bp["Conv_0"]["kernel"], w, dtype=dtype,
                               strides=(strides, strides))
                y = _group_norm_stacked(
                    y, bp["GroupNorm_0"]["scale"], bp["GroupNorm_0"]["bias"],
                    num_workers=w, groups_per_worker=gpw)
                y = nn.relu(y)
                y = _conv_fast(y, bp["Conv_1"]["kernel"], w, dtype=dtype)
                y = _group_norm_stacked(
                    y, bp["GroupNorm_1"]["scale"], bp["GroupNorm_1"]["bias"],
                    num_workers=w, groups_per_worker=gpw)
                if "Conv_2" in bp:
                    residual = _conv_fast(
                        residual, bp["Conv_2"]["kernel"], w, dtype=dtype,
                        strides=(strides, strides))
                    residual = _group_norm_stacked(
                        residual, bp["GroupNorm_2"]["scale"],
                        bp["GroupNorm_2"]["bias"], num_workers=w,
                        groups_per_worker=gpw)
                z = nn.relu(y + residual)
        z = jnp.mean(z, axis=(1, 2))                 # [B, W·C]
        z = z.reshape(b, w, -1)
        hd = fp["head"]
        z = (jnp.einsum("bwi,wio->bwo", z, hd["kernel"].astype(dtype))
             + hd["bias"].astype(dtype)[None])
        z = jnp.moveaxis(z, 1, 0)                    # [W, B, ncls]
        return _head(z, faithful)

    return apply


def _make_stacked_cnn_apply(model: "_ReferenceCNN"):
    """Grouped-stacked forward for the reference CNNs.

    The conv kernels are permuted to the grouped layout AND the FC
    kernels reshaped to their VALID-conv form at the top of each apply
    — a Dense over the flattened [H', Wd', C2] is exactly an H'×Wd'
    VALID conv, and keeping the worker axis in channels end-to-end
    avoids a [W·B·3136] activation relayout between conv and FC whose
    forward+backward transposes cost ~2× the conv time in the einsum
    formulation (measured on v5e).  flax flattens [H', Wd', C2]
    row-major, so the [W, H'·Wd'·C2, O] kernel reshapes to
    [W, H', Wd', C2, O] with matching index order.  (Carrying the
    grouped layout through the training scan instead was measured and
    rejected — see ``_make_stacked_resnet_apply``.)
    """
    faithful, dtype = model.faithful, model.dtype

    def to_fast(p, hp, wp):
        """hp/wp: the post-pool spatial dims, taken from the ACTUAL
        activation shape at the fc1 call site (not inferred by a square
        root — non-square inputs reshape correctly, ADVICE r4)."""
        c2n = p["conv2"]["kernel"].shape[-1]
        f1 = p["fc1"]["kernel"]           # [W, H'·Wd'·C2, hidden]
        if f1.shape[1] != hp * wp * c2n:
            raise ValueError(
                f"fc1 kernel fan-in {f1.shape[1]} != post-pool "
                f"H'·Wd'·C2 = {hp}·{wp}·{c2n}")
        f2 = p["fc2"]["kernel"]           # [W, hidden, ncls]
        return {
            "conv1": {"kernel": _to_grouped_kernel(p["conv1"]["kernel"]),
                      "bias": p["conv1"]["bias"]},
            "conv2": {"kernel": _to_grouped_kernel(p["conv2"]["kernel"]),
                      "bias": p["conv2"]["bias"]},
            "fc1": {"kernel": _to_grouped_kernel(
                        f1.reshape(f1.shape[0], hp, wp, c2n, f1.shape[2])),
                    "bias": p["fc1"]["bias"]},
            "fc2": {"kernel": _to_grouped_kernel(
                        f2.reshape(f2.shape[0], 1, 1, *f2.shape[1:])),
                    "bias": p["fc2"]["bias"]},
        }

    def apply(params, x):
        w, b = x.shape[0], x.shape[1]
        h_in, w_in = x.shape[2], x.shape[3]
        # Post-pool spatial dims after two stride-2 pools (floored —
        # nn.max_pool's odd-dim behaviour).
        hp, wp = h_in // 2 // 2, w_in // 2 // 2
        fp = to_fast(params, hp, wp)
        # [W, B, H, Wd, C] → [B, H, Wd, W·C] (worker-major channels)
        z = jnp.moveaxis(x.astype(dtype), 0, 3)
        z = z.reshape(*z.shape[:3], -1)
        z = _conv_fast(z, fp["conv1"]["kernel"], w, dtype=dtype,
                       bias=fp["conv1"]["bias"])
        if not faithful:
            z = nn.relu(z)
        z = _max_pool_2x2(z)
        z = _conv_fast(z, fp["conv2"]["kernel"], w, dtype=dtype,
                       bias=fp["conv2"]["bias"])
        if not faithful:
            z = nn.relu(z)
        z = _max_pool_2x2(z)          # [B, H', Wd', W·C2]
        z = _conv_fast(z, fp["fc1"]["kernel"], w, dtype=dtype,
                       padding="VALID", bias=fp["fc1"]["bias"])
        z = nn.relu(z)
        # f32 logits layer on the corrected head — mirrors the flax
        # module (see _ReferenceCNN.__call__).
        head_dtype = dtype if faithful else jnp.float32
        z = _conv_fast(z.astype(head_dtype), fp["fc2"]["kernel"], w,
                       dtype=head_dtype, padding="VALID",
                       bias=fp["fc2"]["bias"])
        ncls = z.shape[-1] // w
        z = z.reshape(b, w, ncls)
        z = jnp.moveaxis(z, 1, 0)                 # [W, B, ncls]
        return _head(z, faithful)

    return apply


def resolve_stacked_apply(model, stacked_impl: str):
    """Validate ``ModelConfig.stacked_impl`` and resolve the grouped
    stacked forward for it — the one shared entry point both engines
    use, so the accepted values can never drift between them."""
    if stacked_impl not in ("auto", "vmap"):
        raise ValueError(
            f"unknown stacked_impl {stacked_impl!r}; one of auto|vmap")
    return make_stacked_apply(model) if stacked_impl == "auto" else None


def make_stacked_apply(model) -> "callable | None":
    """Stacked-worker forward for the reference CNNs and the ResNet as
    ONE grouped-conv program — the engine's fast path around
    ``vmap(model.apply)``.

    XLA lowers a conv vmapped over per-worker kernels poorly on TPU
    (layout shuffles around every conv; measured 1.6× step slowdown at
    6 workers and ~4× at 32).  The same math maps exactly onto a single
    ``conv_general_dilated`` with ``feature_group_count=W``: put the
    worker axis into the channel dimension ([W, B, H, Wd, C] →
    [B, H, Wd, W·C]) and concatenate the per-worker kernels into
    [kh, kw, C, W·Cout] — group w then convolves worker w's channels
    with worker w's kernel, which is precisely the stacked-fleet
    forward.  Prototype measurement: 0.43 ms vs 1.43 ms per fused train
    step on the headline workload (v5e).

    Returns ``apply(stacked_params, x)`` mapping a [W, ...]-stacked
    param pytree (the engine's native layout) and [W, B, H, Wd, C]
    inputs to [W, B, num_classes] outputs — bit-comparable to
    ``vmap(model.apply)`` up to float reassociation inside the conv —
    or ``None`` for models without a grouped-stacked form (the engines
    fall back to vmap).
    """
    if isinstance(model, ResNet18):
        return _make_stacked_resnet_apply(model)
    if isinstance(model, _ReferenceCNN):
        return _make_stacked_cnn_apply(model)
    return None


_ZOO = {
    "model1": Model1,
    "model3": Model3,
    "mlp": MLP,
    "logistic": LogisticRegression,
    "resnet18": ResNet18,
    "transformer": TransformerLM,
}


def build_model(
    name: str,
    *,
    num_classes: int = 10,
    faithful: bool | None = None,
    dtype: Any = jnp.float32,
    stage_sizes: Sequence[int] | None = None,
) -> nn.Module:
    """Model dispatch by name — the typed replacement for the reference's
    if/elif on ``args.model`` (``servers.py:33-40``, ``simulators.py:31-38``).

    ``faithful=None`` keeps each model's own default: True only for
    the two reference CNNs (which have a double-softmax to be faithful
    to), False for mlp/logistic/resnet18 (new models, corrected head).
    ``dtype`` may be a string ("bfloat16" → MXU-native compute); params
    stay float32 (flax param_dtype default) — bf16 is compute-only.
    ``stage_sizes`` (resnet18 only) overrides the per-stage block counts
    for shallow variants.
    """
    if isinstance(dtype, str):
        dtype = jnp.dtype(dtype)
    key = name.lower()
    if key not in _ZOO:
        raise ValueError(f"unknown model {name!r}; one of {sorted(_ZOO)}")
    kwargs: dict[str, Any] = dict(num_classes=num_classes, dtype=dtype)
    if faithful is not None:
        kwargs["faithful"] = faithful
    if stage_sizes is not None:
        if key != "resnet18":
            raise ValueError("stage_sizes applies to resnet18 only")
        kwargs["stage_sizes"] = tuple(stage_sizes)
    return _ZOO[key](**kwargs)


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
