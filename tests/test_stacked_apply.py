"""Grouped stacked-forward fast path vs the vmapped per-worker path.

``dopt.models.make_stacked_apply`` reorganises the reference CNNs'
stacked-fleet forward into one feature-grouped conv program (worker
axis in the channel dimension).  The math is identical to
``vmap(model.apply)`` up to float reassociation inside the conv, so
every surface the engines consume — forward, one-step update, the
epoch-structured update with local-val eval, and the evaluators — must
agree within float tolerance, for both reference CNNs and both head
modes.  The engine-level test pins that stacked_impl='auto' and 'vmap'
produce the same training trajectory.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.engine.local import (make_stacked_evaluator,
                               make_stacked_local_update,
                               make_stacked_local_update_epochs,
                               make_stacked_local_update_gather)
from dopt.models import build_model, make_stacked_apply

W, B, S = 3, 8, 4

# The MULTI-STEP parity tests chain S dependent SGD steps through the
# grouped-conv forward: each step's reassociation delta (grouped conv
# vs vmap sums channels in a different order) feeds the next step's
# inputs, and on the CPU backend — whose conv algorithms differ more
# between the two lowerings than the TPU's — the compounded drift
# lands ~3% relative after 4 steps, past any tolerance that would
# still catch real bugs.  Single-step and single-forward parity (the
# actual contract) passes everywhere; the engine-level trajectory test
# pins end-to-end agreement at history precision.  Pre-existing
# failure triaged in PR 6 (ISSUE 5 satellite): expected-fail on CPU,
# strict=False so TPU runs still assert.
_xfail_cpu_multistep = pytest.mark.xfail(
    jax.default_backend() == "cpu",
    reason="CPU conv reassociation compounds over dependent SGD steps "
           "beyond per-step float tolerance (grouped vs vmap lowering); "
           "passes on TPU — see CHANGES.md PR 6 triage",
    strict=False)


def _setup(model_name, faithful):
    shape = (28, 28, 1) if model_name == "model1" else (32, 32, 3)
    model = build_model(model_name, faithful=faithful)
    p0 = model.init(jax.random.key(0), jnp.zeros((1, *shape)))["params"]
    rng = np.random.default_rng(7)
    stacked = jax.tree.map(
        lambda x: jnp.asarray(np.stack([
            np.asarray(x) + 0.01 * i for i in range(W)])), p0)
    x = jnp.asarray(rng.normal(size=(W, B, *shape)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (W, B)).astype(np.int32))
    return model, stacked, x, y


@pytest.mark.parametrize("model_name", ["model1", "model3"])
@pytest.mark.parametrize("faithful", [True, False])
def test_forward_parity(model_name, faithful):
    model, stacked, x, y = _setup(model_name, faithful)
    s_apply = make_stacked_apply(model)
    assert s_apply is not None
    got = jax.jit(s_apply)(stacked, x)
    want = jax.jit(jax.vmap(
        lambda p, xx: model.apply({"params": p}, xx)))(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_unsupported_models_return_none():
    for name in ("mlp", "logistic"):
        assert make_stacked_apply(build_model(name)) is None


def test_resnet_forward_parity():
    """Grouped-stacked ResNet-18 (the north-star model) vs vmap."""
    model = build_model("resnet18", faithful=False)
    p0 = model.init(jax.random.key(1), jnp.zeros((1, 32, 32, 3)))["params"]
    rng = np.random.default_rng(11)
    stacked = jax.tree.map(
        lambda v: jnp.asarray(np.stack([
            np.asarray(v) * (1 + 0.05 * i) for i in range(W)])), p0)
    x = jnp.asarray(rng.normal(size=(W, 4, 32, 32, 3)).astype(np.float32))
    s_apply = make_stacked_apply(model)
    assert s_apply is not None
    got = jax.jit(s_apply)(stacked, x)
    want = jax.jit(jax.vmap(
        lambda p, xx: model.apply({"params": p}, xx)))(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-5)


def test_resnet_update_parity():
    """One SGD step through the grouped-stacked ResNet matches vmap."""
    model = build_model("resnet18", faithful=False)
    p0 = model.init(jax.random.key(1), jnp.zeros((1, 32, 32, 3)))["params"]
    rng = np.random.default_rng(12)
    stacked = jax.tree.map(
        lambda v: jnp.asarray(np.stack([np.asarray(v)] * W)), p0)
    mom = jax.tree.map(jnp.zeros_like, stacked)
    bx = jnp.asarray(rng.normal(size=(W, 2, 4, 32, 32, 3)).astype(np.float32))
    by = jnp.asarray(rng.integers(0, 10, (W, 2, 4)).astype(np.int32))
    bw = jnp.ones((W, 2, 4), jnp.float32)
    s_apply = make_stacked_apply(model)
    kw = dict(lr=0.05, momentum=0.9)
    f_v = make_stacked_local_update(model.apply, **kw)
    f_s = make_stacked_local_update(model.apply, **kw, stacked_apply=s_apply)
    pv, mv, lv, av = jax.jit(f_v)(stacked, mom, bx, by, bw)
    ps, ms, ls, as_ = jax.jit(f_s)(stacked, mom, bx, by, bw)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4), pv, ps)
    np.testing.assert_allclose(np.asarray(lv), np.asarray(ls),
                               rtol=1e-3, atol=1e-4)


@_xfail_cpu_multistep
@pytest.mark.parametrize("algorithm", ["sgd", "fedprox", "fedadmm",
                                       "scaffold"])
def test_local_update_parity(algorithm):
    model, stacked, x, y = _setup("model1", True)
    s_apply = make_stacked_apply(model)
    mom = jax.tree.map(jnp.zeros_like, stacked)
    bx = jnp.stack([x] * S, axis=1)          # [W, S, B, ...]
    by = jnp.stack([y] * S, axis=1)
    bw = jnp.ones((W, S, B), jnp.float32)
    theta = jax.tree.map(lambda v: v[0], stacked)
    # fedadmm: worker-stacked duals; scaffold: theta slot = server
    # control c (broadcast, NONZERO so a slot swap cannot cancel),
    # alpha slot = client controls c_i (stacked).
    alpha = jax.tree.map(
        lambda v: 0.01 * jnp.ones_like(v) * (1 + jnp.arange(W).reshape(
            (W,) + (1,) * (v.ndim - 1))), stacked)
    kw = dict(lr=0.05, momentum=0.5, algorithm=algorithm, rho=0.1)
    args = {"sgd": (stacked, mom, bx, by, bw),
            "fedprox": (stacked, mom, bx, by, bw, theta),
            "fedadmm": (stacked, mom, bx, by, bw, theta, alpha),
            "scaffold": (stacked, mom, bx, by, bw, theta, alpha)}[algorithm]
    f_v = make_stacked_local_update(model.apply, **kw)
    f_s = make_stacked_local_update(model.apply, **kw, stacked_apply=s_apply)
    pv, mv, lv, av = jax.jit(f_v)(*args)
    ps, ms, ls, as_ = jax.jit(f_s)(*args)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), pv, ps)
    np.testing.assert_allclose(np.asarray(lv), np.asarray(ls),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(av), np.asarray(as_), atol=1e-6)


@_xfail_cpu_multistep
def test_gather_and_epochs_parity():
    model, stacked, x, y = _setup("model1", True)
    s_apply = make_stacked_apply(model)
    mom = jax.tree.map(jnp.zeros_like, stacked)
    rng = np.random.default_rng(3)
    n = 64
    tx = jnp.asarray(rng.normal(size=(n, 28, 28, 1)).astype(np.float32))
    ty = jnp.asarray(rng.integers(0, 10, n).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, n, (W, S, B)).astype(np.int32))
    bw = jnp.ones((W, S, B), jnp.float32)
    kw = dict(lr=0.05, momentum=0.5)
    for chunks in (None, 2):
        f_v = make_stacked_local_update_gather(model.apply, **kw,
                                               gather_chunks=chunks)
        f_s = make_stacked_local_update_gather(model.apply, **kw,
                                               gather_chunks=chunks,
                                               stacked_apply=s_apply)
        pv, mv, lv, av = jax.jit(f_v)(stacked, mom, idx, bw, tx, ty)
        ps, ms, ls, as_ = jax.jit(f_s)(stacked, mom, idx, bw, tx, ty)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), pv, ps)
        np.testing.assert_allclose(np.asarray(lv), np.asarray(ls),
                                   rtol=2e-4, atol=2e-5)

    # Epoch-structured variant with per-epoch local-val eval.
    e = 2
    idx_e = idx.reshape(W, e, S // e, B)
    bw_e = bw.reshape(idx_e.shape)
    vi = jnp.asarray(rng.integers(0, n, (W, 2, B)).astype(np.int32))
    vw = jnp.ones((W, 2, B), jnp.float32)
    f_v = make_stacked_local_update_epochs(model.apply, **kw)
    f_s = make_stacked_local_update_epochs(model.apply, **kw,
                                           stacked_apply=s_apply)
    pv, mv, emv = jax.jit(f_v)(stacked, mom, idx_e, bw_e, tx, ty, vi, vw)
    ps, ms, ems = jax.jit(f_s)(stacked, mom, idx_e, bw_e, tx, ty, vi, vw)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), pv, ps)
    assert set(emv) == set(ems)
    for k in emv:
        np.testing.assert_allclose(np.asarray(emv[k]), np.asarray(ems[k]),
                                   rtol=5e-4, atol=5e-5)


def test_evaluator_parity():
    model, stacked, x, y = _setup("model1", True)
    s_apply = make_stacked_apply(model)
    ex = jnp.stack([x[0]] * 2)               # [S=2, B, ...] shared stack
    ey = jnp.stack([y[0]] * 2)
    ew = jnp.ones((2, B), jnp.float32)
    ev_v = make_stacked_evaluator(model.apply)
    ev_s = make_stacked_evaluator(model.apply, stacked_apply=s_apply)
    mv = jax.jit(ev_v)(stacked, ex, ey, ew)
    ms = jax.jit(ev_s)(stacked, ex, ey, ew)
    for k in ("acc", "loss_sum", "loss_mean", "count"):
        np.testing.assert_allclose(np.asarray(mv[k]), np.asarray(ms[k]),
                                   rtol=2e-4, atol=2e-5)


def test_engine_trajectory_parity():
    """GossipTrainer with stacked_impl='auto' vs 'vmap': same history."""
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    def run(impl):
        cfg = ExperimentConfig(
            name=f"stacked-{impl}", seed=5,
            data=DataConfig(dataset="synthetic", num_users=4, iid=False,
                            shards=2, synthetic_train_size=96,
                            synthetic_test_size=32),
            model=ModelConfig(model="model1", faithful=True,
                              stacked_impl=impl),
            optim=OptimizerConfig(lr=0.05, momentum=0.5),
            gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                mode="stochastic", rounds=2, local_ep=1,
                                local_bs=8),
        )
        tr = GossipTrainer(cfg)
        h = tr.run(rounds=2)
        return h.rows

    rows_a, rows_v = run("auto"), run("vmap")
    assert len(rows_a) == len(rows_v)
    for ra, rv in zip(rows_a, rows_v):
        for k in ra:
            if isinstance(ra[k], float):
                assert abs(ra[k] - rv[k]) < 5e-4, (k, ra[k], rv[k])
