"""Chaos soak: a randomized fault cocktail, end-to-end, with invariants.

Runs both engines through crash + corrupt + straggler + msg_drop +
msg_delay + churn simultaneously (the full degraded-network regime from
``dopt.faults``) on a small synthetic workload — plus a third leg,
``gossip-async`` (one-peer exponential topology + staleness-1 async
mixing) under the process-fault storm those modes compose with — and
asserts the three things a robust trainer owes you:

1. **Convergence to tolerance** — the fleet still learns: final train
   loss beats the first round's by a margin, and every logged metric is
   finite (the defenses keep poison out of theta).
2. **Ledger invariants** — every fault row is schema-complete
   ({round, worker, kind, action}, kind in ``dopt.faults.KINDS``, ids
   in range), and a rerun of the identical config reproduces the
   ledger row-for-row (the stateless-draw determinism contract).
3. **Blocked-execution parity** — the fused multi-round ``lax.scan``
   path (quarantine streaks, staleness buffers and push-sum mass ride
   the scan carry) replays the per-round trace bit-identically, so
   chaos runs at clean-run dispatch cost is a free speedup, not a
   different experiment.
4. **Checkpoint invariants** — a run killed mid-soak and resumed from
   its latest auto-checkpoint is bit-identical (History rows AND fault
   ledger) to the continuous run.  ``--kill`` does this the honest way:
   it spawns a child process, SIGKILLs it mid-round-loop, and resumes
   from whatever checkpoint survived; the default does the same
   in-process (deterministic, CI-friendly).
5. **Monitor invariants** (dopt.obs.monitor) — the streaming
   ``HealthMonitor``'s alert sequence is identical across per-round,
   fused-blocked and killed-and-resumed execution of the same seed
   (the canonical-stream guarantee lifted to alerts); the stock rule
   set raises ZERO alerts on clean baseline1/baseline3-shaped runs
   (the false-positive gate); and a deliberately injected divergence —
   a corrupt scale bomb against ``aggregator='mean'`` — MUST fire the
   ``loss_divergence`` rule before the run ends.  ``--report-out``
   writes the legs' HealthReports as one JSON artifact for CI.

The cocktail's knobs are drawn from seeded ranges (``--seed``), so
``--seed N`` gives N distinct-but-reproducible storms.

    python scripts/chaos_soak.py --rounds 8 --seed 0
    python scripts/chaos_soak.py --rounds 8 --engine gossip --kill
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from dopt.config import (CommConfig, DataConfig, ExperimentConfig,  # noqa: E402
                         FaultConfig, FederatedConfig, GossipConfig,
                         ModelConfig, OptimizerConfig)
from dopt.faults import KINDS  # noqa: E402

_DATA = DataConfig(dataset="synthetic", num_users=8, iid=True,
                   synthetic_train_size=512, synthetic_test_size=128)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5)


def cocktail(seed: int) -> tuple[FaultConfig, FaultConfig, FaultConfig,
                                 FaultConfig]:
    """Seeded random draw of the round's fault cocktail: (gossip
    cocktail, federated cocktail, async-gossip cocktail, codec-gossip
    cocktail).  The federated one adds the Byzantine nan liar (screened
    by the always-on non-finite guard) and the heavy straggler deadline
    that staleness-aware aggregation buffers; the gossip one leans on
    the link model + push-sum.  The async one draws only the process
    faults (crash + straggler + churn) at HIGHER rates: link faults
    and push-sum are rejected by ``mixing='async'`` by design (the
    [D+1, n, n] staleness stack already subsumes staleness-1), so the
    storm concentrates on the repairs the diag/off-diag split must
    survive.  The codec one likewise draws only process faults — the
    ``msg_*`` knobs run the per-staleness link engine, which keeps the
    dense wire the bucket codec replaces — so the compression-armed leg
    storms exactly the faults the scatter+codec path composes with."""
    rng = np.random.default_rng([0xC0C7A11, seed])

    def u(lo, hi):
        return float(rng.uniform(lo, hi))

    gossip = FaultConfig(
        crash=u(0.03, 0.1), straggle=u(0.1, 0.3), straggle_frac=0.5,
        msg_drop=u(0.1, 0.25), msg_delay=u(0.1, 0.35), msg_delay_max=2,
        churn=u(0.02, 0.08), churn_span=int(rng.integers(2, 4)))
    fed = FaultConfig(
        crash=u(0.03, 0.1), straggle=u(0.3, 0.6), straggle_frac=0.5,
        straggler_policy="drop", over_select=0.3,
        corrupt=u(0.05, 0.15), corrupt_mode="nan",
        msg_drop=u(0.05, 0.15), msg_delay=u(0.1, 0.3), msg_delay_max=3,
        churn=u(0.02, 0.08), churn_span=int(rng.integers(2, 4)))
    asynk = FaultConfig(
        crash=u(0.08, 0.18), straggle=u(0.1, 0.3), straggle_frac=0.5,
        churn=u(0.05, 0.12), churn_span=int(rng.integers(2, 4)))
    codec = FaultConfig(
        crash=u(0.03, 0.1), straggle=u(0.1, 0.3), straggle_frac=0.5,
        churn=u(0.02, 0.08), churn_span=int(rng.integers(2, 4)))
    return gossip, fed, asynk, codec


def build_cfg(engine: str, seed: int, rounds: int,
              prefetch: bool = False) -> ExperimentConfig:
    # diagnostics="on" everywhere: the soak's canonical-stream equality
    # invariants (per-round vs fused-blocked vs killed-and-resumed)
    # thereby pin the NEW per-round convergence gauges too — the PR 8/10
    # guarantee extended to the diagnostics layer.
    pf = "on" if prefetch else "off"
    gossip_fc, fed_fc, async_fc, codec_fc = cocktail(seed)
    if engine == "gossip":
        return ExperimentConfig(
            name=f"chaos-gossip-{seed}", seed=100 + seed, data=_DATA,
            model=_MODEL, optim=_OPTIM,
            gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                mode="metropolis", rounds=rounds,
                                local_ep=1, local_bs=32,
                                correction="push_sum", prefetch=pf,
                                diagnostics="on"),
            faults=gossip_fc)
    if engine == "gossip-async":
        # The new-mode leg: one-peer exponential schedule + staleness-1
        # mixing, under the process-fault storm.  Every soak invariant
        # (blocked/prefetched/resumed bit-identity, ledger replay,
        # canonical stream + alert parity) applies unchanged.
        return ExperimentConfig(
            name=f"chaos-gossip-async-{seed}", seed=100 + seed,
            data=_DATA, model=_MODEL, optim=_OPTIM,
            gossip=GossipConfig(algorithm="dsgd",
                                topology="one_peer_exp",
                                mode="metropolis", rounds=rounds,
                                local_ep=1, local_bs=32,
                                mixing="async", prefetch=pf,
                                diagnostics="on"),
            faults=async_fc)
    if engine == "gossip-codec":
        # The compression-armed leg: scatter substrate + the per-bucket
        # qsgd codec (error feedback riding the scan carry), under the
        # process-fault storm it composes with.  Every soak invariant
        # applies unchanged — blocked-vs-per-round bit-identity pins
        # the codec's fold-in key stream + EF residual carry, and the
        # kill-and-resume leg exercises the 'comm_residual' checkpoint
        # payload end to end.
        return ExperimentConfig(
            name=f"chaos-gossip-codec-{seed}", seed=100 + seed,
            data=_DATA, model=_MODEL, optim=_OPTIM,
            gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                mode="metropolis", rounds=rounds,
                                local_ep=1, local_bs=32,
                                update_sharding="scatter", prefetch=pf,
                                diagnostics="on"),
            comm=CommConfig(codec="qsgd", chunk=64, min_codec_bytes=256),
            faults=codec_fc)
    return ExperimentConfig(
        name=f"chaos-fed-{seed}", seed=100 + seed, data=_DATA,
        model=_MODEL, optim=_OPTIM,
        federated=FederatedConfig(algorithm="fedavg", frac=0.5,
                                  rounds=rounds, local_ep=1, local_bs=32,
                                  staleness_max=3, staleness_decay=0.5,
                                  prefetch=pf, diagnostics="on"),
        faults=fed_fc)


def build_trainer(engine: str, seed: int, rounds: int,
                  prefetch: bool = False):
    from dopt.engine import FederatedTrainer, GossipTrainer

    cfg = build_cfg(engine, seed, rounds, prefetch=prefetch)
    return (GossipTrainer(cfg) if engine.startswith("gossip")
            else FederatedTrainer(cfg))


def cocktail_rules():
    """The monitor rule set for the cocktail legs: the stock set with
    the drop-rate SLO tightened far below the storm's actual loss rate,
    so the soak's alert-sequence-equality invariant compares real
    firings, not three empty lists."""
    from dopt.obs.rules import default_rules

    return default_rules(drop_rate={"max_rate": 0.05, "window": 4,
                                    "min_rounds": 2})


def check_ledger(history, rounds: int, workers: int) -> int:
    """Schema + range invariants over every fault-ledger row.  Shared
    with the serve soak (scripts/serve_soak.py), whose ledgers carry
    fleet-level rows — control-plane config/drain/pause applications
    and population cohort audits use ``worker == -1``."""
    for row in history.faults:
        assert set(row) == {"round", "worker", "kind", "action"}, row
        assert row["kind"] in KINDS, row
        assert 0 <= row["round"] < rounds, row
        assert -1 <= row["worker"] < workers, row
        if row["worker"] == -1:
            assert row["kind"] in ("control", "cohort"), row
        assert isinstance(row["action"], str) and row["action"], row
    return len(history.faults)


def loss_key(history) -> str:
    return ("avg_train_loss" if "avg_train_loss" in history.rows[0]
            else "train_loss")


def check_convergence(history, tol: float) -> tuple[float, float]:
    k = loss_key(history)
    losses = [r[k] for r in history.rows if k in r]
    assert all(np.isfinite(v) for r in history.rows for v in r.values()), \
        "non-finite metric leaked into History"
    first, last = float(losses[0]), float(losses[-1])
    assert last < first + tol, \
        f"no learning under the cocktail: first={first:.4f} last={last:.4f}"
    return first, last


def soak_one(engine: str, seed: int, rounds: int, tol: float,
             ckpt_dir: str, kill: bool, metrics_sink=None,
             prefetch: bool = False):
    from dopt.obs import (HealthMonitor, JsonlSink, MemorySink, Telemetry,
                          attach, canonical, check_stream)

    w = _DATA.num_users
    print(f"[{engine}] cocktail seed={seed}: continuous run ...")
    cont = build_trainer(engine, seed, rounds)
    mem = MemorySink()
    sinks = [mem] + ([metrics_sink] if metrics_sink is not None else [])
    tele_c = Telemetry(sinks)
    # The streaming monitor rides the continuous run IN-PROCESS (sink
    # attachment): alerts fire while it trains and are forwarded into
    # the stream.
    mon_c = HealthMonitor(cocktail_rules()).attach(tele_c)
    attach(cont, tele_c, fresh=True)
    hc = cont.run(rounds=rounds)
    first, last = check_convergence(hc, tol)
    n_rows = check_ledger(hc, rounds, w)
    print(f"[{engine}] loss {first:.4f} -> {last:.4f}, "
          f"{n_rows} ledger rows, kinds "
          f"{sorted(set(r['kind'] for r in hc.faults))}")

    # Telemetry-stream invariants (dopt.obs): every event is
    # schema-valid and the round sequence is gapless and
    # duplicate-free; the typed fault events mirror the ledger 1:1.
    summary = check_stream(mem.events)
    assert summary["rounds"] == rounds, summary
    assert summary["kinds"].get("fault", 0) == n_rows, summary
    # Diagnostics invariants (diagnostics="on"): every round bundle
    # carries the convergence gauges — their cross-path equality is
    # pinned by the canonical-stream asserts below — and the
    # non-deterministic resource channel sampled at least once.
    from dopt.obs.events import DIAG_GAUGES

    gauge_names = {e["name"] for e in mem.events if e["kind"] == "gauge"}
    want = set(DIAG_GAUGES) | {"consensus_distance"
                               if engine.startswith("gossip")
                               else "lane_dispersion"}
    assert want <= gauge_names, \
        f"diagnostic gauges missing from the stream: {want - gauge_names}"
    assert summary["kinds"].get("resource", 0) >= 1, summary
    print(f"[{engine}] telemetry stream ok: {summary['events']} events "
          f"({summary['kinds']}; diagnostics gauges present)")

    # Determinism: the identical config replays the identical storm.
    rerun = build_trainer(engine, seed, rounds)
    hr = rerun.run(rounds=rounds)
    assert hr.rows == hc.rows and hr.faults == hc.faults, \
        "rerun diverged from the first run (stateless-draw contract broken)"
    print(f"[{engine}] deterministic replay ok")

    # Blocked-execution parity: the fused lax.scan path (push-sum mass
    # / staleness buffers / quarantine streaks as scan carry) must
    # replay the identical trace — History rows AND ledger, content
    # and order — at chaos-cocktail settings.  This is the degraded
    # path the throughput work fused; bit-identity is what makes the
    # speedup free.
    # With --prefetch, the blocked trainer runs the staged host
    # pipeline (dispatch → stage-next → fetch): the assertion then pins
    # prefetched-blocked against unprefetched-per-round — the full
    # bit-identity claim of the overlap work.
    blk = build_trainer(engine, seed, rounds, prefetch=prefetch)
    mem_b = MemorySink()
    tele_b = Telemetry([mem_b])
    mon_b = HealthMonitor(cocktail_rules()).attach(tele_b)
    attach(blk, tele_b, fresh=True)
    hb = blk.run(rounds=rounds, block=max(rounds // 2, 2))
    assert hb.rows == hc.rows, \
        f"blocked History diverged from per-round ({engine})"
    assert hb.faults == hc.faults, \
        f"blocked fault ledger diverged from per-round ({engine})"
    assert canonical(mem_b.events) == canonical(mem.events), \
        f"blocked telemetry stream diverged from per-round ({engine})"
    assert mon_b.canonical_alerts() == mon_c.canonical_alerts(), \
        f"blocked-run alert sequence diverged from per-round ({engine})"
    print(f"[{engine}] fused-block execution bit-identical ok "
          f"(History + ledger + event stream + {len(mon_c.alerts)} "
          f"alerts{', prefetch armed' if prefetch else ''})")

    # Kill-and-resume bit-identity, including the telemetry stream's
    # monotonic round watermark: the resumed run APPENDS to the dead
    # run's JSONL and the merged file must carry every round exactly
    # once.
    path = os.path.join(ckpt_dir, f"{engine}-{seed}")
    mpath = os.path.join(ckpt_dir, f"{engine}-{seed}-metrics.jsonl")
    # A persistent --ckpt-dir may hold a previous invocation's stream;
    # the child opens it resume=True and a stale watermark would
    # suppress this run's emission entirely — start from a clean file.
    if os.path.exists(mpath):
        os.unlink(mpath)
    kill_at = max(rounds // 2, 1)
    if kill:
        _sigkill_child(engine, seed, rounds, kill_at, path, mpath)
    else:
        part = build_trainer(engine, seed, rounds)
        tele_p = Telemetry.to_jsonl(mpath)
        attach(part, tele_p)
        part.run(rounds=kill_at, checkpoint_every=1, checkpoint_path=path)
        tele_p.close()
    res = build_trainer(engine, seed, rounds)
    res.restore(path)
    assert res.round >= 1, "no checkpoint survived the kill"
    tele_r = Telemetry.to_jsonl(mpath, resume=True)
    attach(res, tele_r)
    hk = res.run(rounds=rounds - res.round)
    tele_r.close()
    assert hk.rows == hc.rows, \
        f"resumed History diverged from continuous ({engine})"
    assert hk.faults == hc.faults, \
        f"resumed fault ledger diverged from continuous ({engine})"
    merged = JsonlSink.read(mpath)
    check_stream(merged)
    got = [e["round"] for e in merged if e["kind"] == "round"]
    assert got == list(range(rounds)), \
        f"resumed stream rounds {got} != 0..{rounds - 1} ({engine})"
    assert (canonical(merged, kinds=("round", "fault"))
            == canonical(mem.events, kinds=("round", "fault"))), \
        f"resumed telemetry stream diverged from continuous ({engine})"
    # The monitor over the MERGED killed-and-resumed stream (the resume
    # header keeps the rule windows) fires the same alert sequence the
    # continuous in-process monitor did.
    mon_r = HealthMonitor(cocktail_rules())
    mon_r.feed(merged)
    assert mon_r.canonical_alerts() == mon_c.canonical_alerts(), \
        f"resumed-stream alert sequence diverged from continuous ({engine})"
    print(f"[{engine}] {'SIGKILL' if kill else 'in-process kill'}"
          f"-and-resume bit-identical ok (stream watermark gapless, "
          f"alert sequence identical)")
    return mon_c.report()


def clean_baseline_gate(rounds: int):
    """False-positive gate: the STOCK rule set must raise zero alerts
    on clean baseline1/baseline3-shaped runs (the preset's algorithm /
    topology / optimizer, soak-scale synthetic data, and the mlp model
    — model1 is CPU-unviable in CI, the bench --quick precedent).  A
    monitor that cries wolf on a healthy run is worse than no monitor.
    Returns {preset_name: HealthReport}."""
    import dataclasses

    from dopt.obs import HealthMonitor, MemorySink, Telemetry, attach
    from dopt.presets import PRESETS

    reports = {}
    for name in ("baseline1", "baseline3"):
        cfg = PRESETS[name]()
        cfg = dataclasses.replace(
            cfg,
            data=dataclasses.replace(
                cfg.data, synthetic_train_size=_DATA.synthetic_train_size,
                synthetic_test_size=_DATA.synthetic_test_size),
            model=_MODEL)
        if cfg.gossip is not None:
            cfg = dataclasses.replace(
                cfg, gossip=dataclasses.replace(
                    cfg.gossip, rounds=rounds, local_ep=1, local_bs=32))
            from dopt.engine import GossipTrainer as Trainer
        else:
            cfg = dataclasses.replace(
                cfg, federated=dataclasses.replace(
                    cfg.federated, rounds=rounds, local_ep=1, local_bs=32))
            from dopt.engine import FederatedTrainer as Trainer
        print(f"[clean] {name}: {rounds} rounds, stock rule set ...")
        trainer = Trainer(cfg)
        tele = Telemetry([MemorySink()])
        mon = HealthMonitor().attach(tele)   # stock default_rules()
        attach(trainer, tele, fresh=True)
        trainer.run(rounds=rounds)
        rep = mon.report()
        assert rep.alerts == 0 and rep.verdict == "healthy", \
            (f"false-positive gate: clean {name} run raised "
             f"{rep.alerts} alerts: {mon.canonical_alerts()}")
        print(f"[clean] {name}: verdict={rep.verdict}, 0 alerts ok")
        reports[name] = rep
    return reports


def divergence_gate(rounds: int):
    """Detection gate: a corrupt scale bomb (persistent adversaries
    blowing their update up 30x) against the UNDEFENDED mean
    aggregator must diverge the fleet — and the monitor's
    loss_divergence rule MUST fire before the run ends.  30x is the
    PROGRESSIVE regime: the loss rises finitely for a few rounds
    before overflowing, so the divergence rule (which needs a finite
    trailing median) catches it before the NaN does — a bigger bomb
    (1e3) jumps straight to non-finite and only loss_nonfinite can
    see it.  Returns the HealthReport."""
    from dopt.engine import FederatedTrainer
    from dopt.obs import HealthMonitor, MemorySink, Telemetry, attach

    cfg = ExperimentConfig(
        name="chaos-divergence-bomb", seed=7, data=_DATA, model=_MODEL,
        optim=_OPTIM,
        federated=FederatedConfig(algorithm="fedavg", frac=0.5,
                                  rounds=rounds, local_ep=1, local_bs=32),
        faults=FaultConfig(corrupt=1.0, corrupt_max=2,
                           corrupt_mode="scale", corrupt_scale=30.0))
    print(f"[divergence] scale bomb vs aggregator='mean': {rounds} "
          "rounds ...")
    trainer = FederatedTrainer(cfg)
    tele = Telemetry([MemorySink()])
    mon = HealthMonitor().attach(tele)
    attach(trainer, tele, fresh=True)
    trainer.run(rounds=rounds)
    rep = mon.report()
    fired = {a["rule"] for a in mon.alerts}
    assert "loss_divergence" in fired, \
        (f"divergence gate: the scale bomb did not fire loss_divergence "
         f"(fired: {sorted(fired)}; report {rep.to_dict()})")
    assert not rep.ok, f"divergence must be CRITICAL: {rep.to_dict()}"
    print(f"[divergence] fired {sorted(fired)} -> verdict "
          f"{rep.verdict} ok")
    return rep


def _sigkill_child(engine: str, seed: int, rounds: int, kill_at: int,
                   path: str, metrics_path: str | None = None) -> None:
    """Spawn this script as a child running the soak config with
    per-round auto-checkpoints, SIGKILL it once it reports ``kill_at``
    completed rounds, and leave its latest checkpoint (and telemetry
    stream prefix — the JSONL sink flushes per event, so the kill
    leaves a complete prefix) for the caller."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", engine,
           "--seed", str(seed), "--rounds", str(rounds), "--ckpt", path]
    if metrics_path:
        cmd += ["--child-metrics", metrics_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                             env=env)
    try:
        for line in child.stdout:
            if line.startswith("ROUND "):
                done = int(line.split()[1]) + 1
                if done >= kill_at:
                    os.kill(child.pid, signal.SIGKILL)
                    break
    finally:
        child.stdout.close()
        child.wait()
    # Give the filesystem a beat; the checkpoint write itself is atomic
    # (temp dir + rename), so whatever is at `path` is complete.
    time.sleep(0.2)


def child_main(engine: str, seed: int, rounds: int, path: str,
               metrics_path: str | None = None) -> int:
    trainer = build_trainer(engine, seed, rounds)
    if metrics_path:
        from dopt.obs import Telemetry, attach

        # resume=True: a fresh file starts at watermark 0, a restarted
        # child appends past what it already streamed.
        attach(trainer, Telemetry.to_jsonl(metrics_path, resume=True))
    for _ in range(rounds):
        trainer.run(rounds=1, checkpoint_every=1, checkpoint_path=path)
        print(f"ROUND {trainer.round - 1}", flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0,
                    help="cocktail seed (each seed is a different storm)")
    ap.add_argument("--engine",
                    choices=["all", "both", "gossip", "gossip-async",
                             "gossip-codec", "federated"],
                    default="all",
                    help="'all' runs the sync-gossip, async-gossip, "
                         "codec-gossip and federated legs; 'both' is "
                         "the legacy sync-gossip + federated pair")
    ap.add_argument("--tol", type=float, default=0.0,
                    help="slack added to the final-loss-beats-first check")
    ap.add_argument("--kill", action="store_true",
                    help="kill-and-resume via a real SIGKILLed subprocess "
                         "instead of the in-process stop")
    ap.add_argument("--prefetch", action="store_true",
                    help="arm the prefetched host pipeline "
                         "(GossipConfig/FederatedConfig.prefetch='on') "
                         "on the blocked-parity trainer, so the soak's "
                         "bit-identity invariant exercises the staged "
                         "dispatch → stage-next → fetch path against "
                         "the unprefetched per-round trace")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint scratch dir (default: a temp dir)")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="stream the continuous soak runs' telemetry "
                         "(dopt.obs JSONL, one segment per engine) here "
                         "— the CI artifact; validate with "
                         "'python -m dopt.obs.check PATH'")
    ap.add_argument("--report-out", default=None, metavar="PATH",
                    help="write the legs' HealthReports (cocktail "
                         "monitors + clean false-positive gate + "
                         "divergence gate) as one JSON artifact here")
    ap.add_argument("--skip-gates", action="store_true",
                    help="run only the cocktail legs (skip the clean "
                         "false-positive and divergence-detection "
                         "monitor gates)")
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--ckpt", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--child-metrics", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return child_main(args.child, args.seed, args.rounds, args.ckpt,
                          args.child_metrics)

    import tempfile

    engines = {"all": ["gossip", "gossip-async", "gossip-codec",
                       "federated"],
               "both": ["gossip", "federated"]}.get(args.engine,
                                                    [args.engine])
    metrics_sink = None
    if args.metrics_out:
        from dopt.obs import JsonlSink

        metrics_sink = JsonlSink(args.metrics_out)
    reports = {}
    with tempfile.TemporaryDirectory() as tmp:
        ckpt_dir = args.ckpt_dir or tmp
        for engine in engines:
            reports[f"cocktail_{engine}"] = soak_one(
                engine, args.seed, args.rounds, args.tol, ckpt_dir,
                args.kill, metrics_sink=metrics_sink,
                prefetch=args.prefetch)
    if not args.skip_gates:
        for name, rep in clean_baseline_gate(args.rounds).items():
            reports[f"clean_{name}"] = rep
        reports["divergence_bomb"] = divergence_gate(args.rounds)
    if metrics_sink is not None:
        metrics_sink.close()
        print(f"wrote telemetry stream to {args.metrics_out}")
    if args.report_out:
        import json

        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(args.report_out, json.dumps(
            {k: r.to_dict() for k, r in reports.items()}, indent=2))
        print(f"wrote health reports to {args.report_out}")
    print("chaos soak passed: convergence + ledger + checkpoint + "
          "telemetry-stream + monitor invariants hold under the full "
          "cocktail")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
