"""Replay the reference's full experiment grid and commit the artifacts.

The reference ships its experiment outputs as ``Distributed
Optimization/src/results/*.csv`` (9 history dumps) plus saved notebook
cell outputs; this script is the dopt equivalent: it runs the same
experiment grid (P2 ``Weighted Average.ipynb`` cells 14-36 and the P1
federated trio, as presets) on whatever accelerator is present and
writes ``results/*.csv`` in the reference's filename style, comparison
plots, and a summary table.

Data note: this environment has no network egress, so the runs use the
deterministic synthetic dataset at MNIST scale.  Absolute accuracies
therefore differ from the reference's committed CSVs (which used real
MNIST); the *qualitative* structure the reference's plots exhibit —
centralized best, no-consensus collapsing under non-IID, complete >
circle > star mixing for non-IID gossip — is what these artifacts
demonstrate, plus the exact history schema.  Drop raw MNIST files under
``DOPT_DATA_DIR`` and re-run for real-data curves.

Usage: python scripts/replay_reference.py [--smoke] [--out DIR]
(--smoke writes to results-smoke by default; the committed full-run
artifacts in results/ are only touched by an explicit full run)
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# (preset name, reference-style csv stem, reference final acc from BASELINE.md)
GOSSIP_GRID = [
    ("reference-centralized", "centeral_mnist", 0.97),
    ("reference-nocons-iid", "no_cons_iidTrue_mnist", 0.93),
    ("reference-nocons-noniid", "no_cons_iidFalse_mnist", 0.23),
    ("reference-dsgd-star", "dec_fed_avg_star_stochastic_False_mnist", 0.29),
    ("reference-dsgd-circle", "dec_fed_avg_circle_stochastic_False_mnist", 0.46),
    ("reference-dsgd-complete", "dec_fed_avg_compelete_stochastic_False_mnist", 0.82),
    ("reference-dsgd-circle-double",
     "dec_fed_avg_circle_double_stochastic_False_mnist", 0.38),
    ("reference-dsgd-complete-double",
     "dec_fed_avg_compelete_double_stochastic_False_mnist", 0.78),
    # Cell 29's mode='dynamic' quirk run: raw 0/1 complete-graph weights
    # (the reference's committed dec_fed_avg_dynamic_* CSVs are empty;
    # the notebook cell output is the 0.32 baseline).
    ("reference-dsgd-dynamic", "dec_fed_avg_dynamic_ones_False_mnist", 0.32),
    ("reference-fedlcon", "fedlcon_circle_stochastic_False_mnist", 0.74),
    ("reference-gossip", "gossip_learning_matching_False_mnist", None),
]
FED_GRID = [
    ("reference-fedavg", "fed_avg_mnist_20_100", 0.9782),
    ("reference-fedprox", "fed_prox_mnist_20_100", 0.9768),
    ("reference-fedadmm", "fed_admm_mnist_20_100", 0.9747),
    ("reference-scaffold", "scaffold_mnist_20_100", None),
]


def run_preset(name: str, *, scale: float, rounds: int | None):
    import dataclasses

    from dopt.presets import get_preset
    from dopt.run import build_trainer

    cfg = get_preset(name)
    if scale != 1.0:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data,
            synthetic_train_size=max(int(cfg.data.synthetic_train_size * scale),
                                     cfg.data.num_users * 8),
            synthetic_test_size=max(int(cfg.data.synthetic_test_size * scale), 64),
        ))
    trainer = build_trainer(cfg)
    t0 = time.time()
    trainer.run(rounds=rounds)
    return trainer, time.time() - t0


# Qualitative orderings the committed synthetic grid exhibits (final
# avg_test_acc).  These are the structure the replay demonstrates — a
# regression that flips one must fail loudly (VERDICT r2 weak #3).
# Note the synthetic grid's star/circle ordering is the OPPOSITE of the
# reference's real-MNIST one (star 0.6954 > circle 0.6416 here vs
# 0.29 < 0.46 there); we pin what our grid actually shows.  Only
# fedlcon > CIRCLE is pinned (star vs fedlcon is deliberately left
# unpinned: committed values 0.7546 vs 0.6954 are close enough that a
# benign rerun could swap them); star is pinned above circle and above
# nocons-noniid via circle.
ORDERINGS = [
    ("reference-centralized", ">=", "reference-dsgd-complete"),
    ("reference-dsgd-complete", ">", "reference-fedlcon"),
    ("reference-fedlcon", ">", "reference-dsgd-circle"),
    ("reference-dsgd-star", ">", "reference-dsgd-circle"),
    ("reference-dsgd-circle", ">", "reference-nocons-noniid"),
    ("reference-dsgd-complete-double", ">", "reference-dsgd-circle-double"),
    ("reference-nocons-iid", ">", "reference-nocons-noniid"),
    # The cell-29 raw-0/1-weights quirk run: unnormalised mixing rows
    # (sum n−1) blow the consensus up, so it lands far below the
    # properly-weighted complete graph (reference: 0.32 vs 0.82 on real
    # MNIST; committed synthetic grid: 0.1021 vs 0.9559).
    ("reference-dsgd-complete", ">", "reference-dsgd-dynamic"),
]


def check_orderings(summary: list[dict]) -> list[str]:
    """Return human-readable violations of ORDERINGS (empty = pass)."""
    acc = {r["preset"]: r.get("final_acc") for r in summary}
    problems = []
    for a, op, b in ORDERINGS:
        va, vb = acc.get(a), acc.get(b)
        if va is None or vb is None:
            problems.append(f"missing preset for ordering {a} {op} {b}")
            continue
        ok = va >= vb if op == ">=" else va > vb
        if not ok:
            problems.append(f"{a} ({va}) !{op} {b} ({vb})")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny data / few rounds (machinery check only)")
    ap.add_argument("--check", action="store_true",
                    help="validate <out>/summary.json against the pinned "
                         "qualitative orderings and exit (no training)")
    ap.add_argument("--out", default=None,
                    help="output dir (default: results, or results-smoke "
                         "under --smoke so a machinery check can never "
                         "clobber the committed full-run artifacts)")
    ap.add_argument("--skip-federated", action="store_true")
    ap.add_argument("--skip-gossip", action="store_true")
    ap.add_argument("--only", nargs="*", default=None,
                    help="run only these presets (rows merge into the "
                         "existing summary.json by preset name)")
    args = ap.parse_args()

    out = Path(args.out or ("results-smoke" if args.smoke else "results"))
    if args.check:
        summary = json.loads((out / "summary.json").read_text())
        problems = check_orderings(summary)
        for p in problems:
            print(f"ORDERING VIOLATION: {p}", file=sys.stderr)
        print(f"checked {len(ORDERINGS)} orderings on {out}/summary.json: "
              f"{'FAIL' if problems else 'ok'}", file=sys.stderr)
        return 1 if problems else 0
    out.mkdir(parents=True, exist_ok=True)
    scale = 0.02 if args.smoke else 1.0
    gossip_rounds = 2 if args.smoke else None
    fed_rounds = 2 if args.smoke else None

    from dopt.utils.plotting import compare_histories

    summary = []
    gossip_histories = {}
    gossip_grid = [] if args.skip_gossip else GOSSIP_GRID
    fed_grid = [] if args.skip_federated else FED_GRID
    if args.only is not None:
        gossip_grid = [r for r in gossip_grid if r[0] in args.only]
        fed_grid = [r for r in fed_grid if r[0] in args.only]
        missing = set(args.only) - {r[0] for r in gossip_grid + fed_grid}
        if missing:
            ap.error(f"unknown presets: {sorted(missing)}")
    for preset, stem, ref_acc in gossip_grid:
        trainer, dt = run_preset(preset, scale=scale, rounds=gossip_rounds)
        csv = out / f"{stem}_{trainer.round}rounds_{trainer.num_workers}users.csv"
        trainer.history.to_csv(csv)
        acc = trainer.history.last().get("avg_test_acc")
        gossip_histories[preset.removeprefix("reference-")] = trainer.history
        summary.append({"preset": preset, "csv": csv.name,
                        "final_acc": round(float(acc), 4) if acc is not None else None,
                        "reference_acc": ref_acc, "seconds": round(dt, 2)})
        print(json.dumps(summary[-1]), flush=True)

    if gossip_histories and args.only is None:
        # Partial (--only) reruns skip the grid plot — it would render
        # only the rerun subset over the committed full-grid image.
        compare_histories(
            gossip_histories,
            metrics=("avg_test_acc", "avg_test_loss", "avg_train_loss"),
            title="dopt replay of the reference gossip grid (synthetic MNIST-scale data)",
            save=out / "gossip_grid_comparison.png",
        )

    if fed_grid:
        fed_histories = {}
        for preset, stem, ref_acc in fed_grid:
            trainer, dt = run_preset(preset, scale=scale, rounds=fed_rounds)
            csv = out / f"{stem}.csv"
            trainer.history.to_csv(csv)
            acc = trainer.history.last().get("test_acc")
            fed_histories[preset.removeprefix("reference-")] = trainer.history
            summary.append({"preset": preset, "csv": csv.name,
                            "final_acc": round(float(acc), 4) if acc is not None else None,
                            "reference_acc": ref_acc, "seconds": round(dt, 2)})
            print(json.dumps(summary[-1]), flush=True)
        if args.only is None:
            compare_histories(
                fed_histories,
                metrics=("test_acc", "test_loss", "train_loss"),
                title="dopt replay of the reference federated trio + SCAFFOLD",
                save=out / "federated_comparison.png",
            )

    path = out / "summary.json"
    if path.exists():  # merge partial reruns by preset name
        old = {r["preset"]: r for r in json.loads(path.read_text())}
        old.update({r["preset"]: r for r in summary})
        summary = list(old.values())
    path.write_text(json.dumps(summary, indent=2))
    print(f"wrote {len(summary)} runs to {out}/", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
