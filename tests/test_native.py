"""Native (C++) host-runtime batch planner: contract + determinism.

The native planner shares the numpy planner's contract (every epoch
block is a permutation of the worker's index row; wraparound padding
with 0-weight tail) but uses its own RNG stream — so tests check the
CONTRACT, not byte equality with numpy.
"""

import numpy as np
import pytest

from dopt.data.pipeline import make_batch_plan
from dopt.native import fill_batch_plan_native, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain / native build failed"
)


def _index_matrix(w=4, l=37, base=100):
    rng = np.random.default_rng(0)
    return np.stack([rng.permutation(l) + base * (i + 1) for i in range(w)]).astype(np.int32)


def test_native_plan_contract():
    im = _index_matrix()
    idx, weight = fill_batch_plan_native(im, batch_size=8, local_ep=3,
                                         seed=7, round_idx=2)
    w, l = im.shape
    steps_per_epoch = -(-l // 8)
    assert idx.shape == (w, 3 * steps_per_epoch, 8)
    assert weight.shape == idx.shape
    for wi in range(w):
        for ep in range(3):
            block = idx[wi, ep * steps_per_epoch:(ep + 1) * steps_per_epoch]
            flat = block.reshape(-1)
            # Real (weight-1) entries are exactly a permutation of the row.
            wflat = weight[wi, ep * steps_per_epoch:(ep + 1) * steps_per_epoch].reshape(-1)
            real = flat[wflat == 1.0]
            np.testing.assert_array_equal(np.sort(real), np.sort(im[wi]))
            # Padding wraps around from the head of the permutation.
            pad = flat[wflat == 0.0]
            np.testing.assert_array_equal(pad, flat[:len(pad)])


def test_native_plan_deterministic_and_round_varying():
    im = _index_matrix()
    a = fill_batch_plan_native(im, batch_size=8, local_ep=2, seed=7, round_idx=0)
    b = fill_batch_plan_native(im, batch_size=8, local_ep=2, seed=7, round_idx=0)
    c = fill_batch_plan_native(im, batch_size=8, local_ep=2, seed=7, round_idx=1)
    d = fill_batch_plan_native(im, batch_size=8, local_ep=2, seed=8, round_idx=0)
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])
    assert not np.array_equal(a[0], d[0])
    # Different epochs within one call shuffle differently.
    steps = a[0].shape[1] // 2
    assert not np.array_equal(a[0][:, :steps], a[0][:, steps:])


def test_native_plan_drop_last():
    im = _index_matrix(l=40)
    idx, weight = fill_batch_plan_native(im, batch_size=16, local_ep=1,
                                         seed=1, round_idx=0, drop_last=True)
    assert idx.shape == (4, 2, 16)  # 40 // 16 = 2 steps, 8 samples dropped
    assert (weight == 1.0).all()


def test_make_batch_plan_native_impl_dispatch():
    im = _index_matrix()
    plan = make_batch_plan(im, batch_size=8, local_ep=2, seed=3, round_idx=5,
                           impl="native")
    ref = fill_batch_plan_native(im, batch_size=8, local_ep=2, seed=3,
                                 round_idx=5)
    np.testing.assert_array_equal(plan.idx, ref[0])
    np.testing.assert_array_equal(plan.weight, ref[1])
    # numpy impl still the default and differs in stream, same contract
    py = make_batch_plan(im, batch_size=8, local_ep=2, seed=3, round_idx=5)
    assert py.idx.shape == plan.idx.shape
    assert not np.array_equal(py.idx, plan.idx)


def test_native_plan_worker_subset_matches_full_plan_rows():
    if not native_available():
        pytest.skip("native library unavailable")
    mat = np.arange(8 * 100, dtype=np.int64).reshape(8, 100)
    full = make_batch_plan(mat, batch_size=32, local_ep=2, seed=7,
                           round_idx=3, impl="native")
    sel = np.array([0, 3, 7])
    sub = make_batch_plan(mat, batch_size=32, local_ep=2, seed=7,
                          round_idx=3, impl="native", workers=sel)
    assert sub.idx.shape == (3, 8, 32)
    np.testing.assert_array_equal(sub.idx, full.idx[sel])
    np.testing.assert_array_equal(sub.weight, full.weight[sel])
