"""Collective mixing ops vs numpy ground truth on an 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.parallel.collectives import (
    broadcast_to_workers,
    masked_average,
    mix_dense,
    mix_power,
    mix_shifts_shardmap,
)
from dopt.parallel.mesh import make_mesh, shard_worker_tree, worker_sharding
from dopt.topology import build_mixing_matrices, shift_decomposition


def _tree(w, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": jnp.asarray(rng.normal(size=(w, 5, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(w, 7)).astype(np.float32)),
    }


def _np_mix(w_matrix, tree):
    return {k: np.tensordot(w_matrix, np.asarray(v), axes=[[1], [0]]).astype(np.float32)
            for k, v in tree.items()}


@pytest.mark.parametrize("topology,mode", [
    ("circle", "stochastic"),
    ("complete", "double_stochastic"),
    ("star", "stochastic"),
    ("dynamic", "stochastic"),
])
def test_mix_dense_matches_numpy(devices, topology, mode):
    mesh = make_mesh(8)
    mm = build_mixing_matrices(topology, mode, 8, seed=3)
    tree = shard_worker_tree(_tree(8), mesh)
    out = jax.jit(mix_dense)(tree, mm.matrices[0])
    want = _np_mix(mm.matrices[0], tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), want[k], rtol=2e-5, atol=1e-6)


def test_mix_dense_sharded_output_stays_sharded(devices):
    mesh = make_mesh(8)
    tree = shard_worker_tree(_tree(8), mesh)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    out = jax.jit(lambda t, w: mix_dense(t, w, mesh))(tree, mm.matrices[0])
    assert out["a"].sharding.is_equivalent_to(worker_sharding(mesh), out["a"].ndim)


def test_mix_shifts_shardmap_matches_dense(devices):
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    shifts = shift_decomposition(mm.matrices[0])
    assert shifts is not None and len(shifts) == 3
    tree = shard_worker_tree(_tree(8), mesh)
    out_shift = mix_shifts_shardmap(tree, shifts, mesh)
    want = _np_mix(mm.matrices[0], tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out_shift[k]), want[k], rtol=2e-5, atol=1e-6)


def test_mix_shifts_ring_stochastic(devices):
    # Row-stochastic zero-diagonal ring (the faithful reference matrix).
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "stochastic", 8, seed=11)
    shifts = shift_decomposition(mm.matrices[0])
    tree = shard_worker_tree(_tree(8, seed=4), mesh)
    out = mix_shifts_shardmap(tree, shifts, mesh)
    want = _np_mix(mm.matrices[0], tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), want[k], rtol=2e-5, atol=1e-6)


def test_mix_shifts_folded_lanes_matches_numpy(devices):
    """Workers folded onto devices (n=32 on 8 devices, 4 lanes each):
    every circulant shift class — pure device rotation (r=0), pure lane
    shift (q=0), and straddling both — must reproduce W @ x exactly."""
    from dopt.parallel.collectives import device_rotations, mix_shifts
    from dopt.topology import coeffs_for_matrix

    n, d = 32, 8
    mesh = make_mesh(d)
    rng = np.random.default_rng(5)
    # Arbitrary circulant with shifts exercising r=0 (s=8), q=0 (s=1,3),
    # and straddles (s=5, s=31 wraps device 7 -> 0).
    shift_ids = (0, 1, 3, 5, 8, 31)
    w = np.zeros((n, n))
    for s in shift_ids:
        w[np.arange(n), (np.arange(n) + s) % n] = rng.random(n)
    w /= w.sum(axis=1, keepdims=True)
    coeffs = coeffs_for_matrix(w, shift_ids)
    tree = shard_worker_tree(_tree(n, seed=9), mesh)
    out = mix_shifts(tree, shift_ids, coeffs, mesh)
    want = _np_mix(w.astype(np.float32), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), want[k],
                                   rtol=2e-5, atol=1e-6)
    # Rotation dedup: the six global shifts need only three nonzero
    # device hops (s=0,1,3 are local/+1; s=5 adds +2; s=8 reuses +2;
    # s=31 adds +7 and wraps back to local).
    assert device_rotations(shift_ids, n // d, d) == (1, 2, 7)
    # Lane-sliced shipping: rotation +1 and +2 need their full 4-lane
    # blocks (s=8 consumes all of +2), but rotation +7 ships only the
    # single lane s=31 consumes — 9 lane-shards total, not 3×4.
    from dopt.parallel.collectives import shift_comm_lanes

    assert shift_comm_lanes(shift_ids, n // d, d) == 9
    # The north-star folded ring ships exactly 2 single-lane shards.
    assert shift_comm_lanes((0, 1, 31), 4, 8) == 2


def test_mix_shifts_folded_comm_compression_bf16(devices):
    from dopt.parallel.collectives import mix_shifts
    from dopt.topology import coeffs_for_matrix, build_mixing_matrices

    n, mesh = 16, make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", n)
    shift_ids = (0, 1, n - 1)
    coeffs = coeffs_for_matrix(mm.matrices[0], shift_ids)
    tree = shard_worker_tree(_tree(n, seed=2), mesh)
    exact = mix_shifts(tree, shift_ids, coeffs, mesh)
    comp = mix_shifts(tree, shift_ids, coeffs, mesh, comm_dtype=jnp.bfloat16)
    for k in tree:
        assert comp[k].dtype == tree[k].dtype
        np.testing.assert_allclose(np.asarray(comp[k]), np.asarray(exact[k]),
                                   atol=0.03, rtol=0.03)


def test_masked_average_uniform_over_sampled(devices):
    mesh = make_mesh(8)
    tree = shard_worker_tree(_tree(8), mesh)
    mask = np.array([1, 0, 1, 0, 0, 0, 1, 0], np.float32)
    theta = jax.jit(masked_average)(tree, mask)
    for k in tree:
        want = np.asarray(tree[k])[mask.astype(bool)].mean(axis=0)
        np.testing.assert_allclose(np.asarray(theta[k]), want, rtol=2e-5, atol=1e-6)
        assert theta[k].shape == tree[k].shape[1:]


def test_broadcast_roundtrip(devices):
    tree = {"p": jnp.arange(6.0).reshape(2, 3)}
    out = broadcast_to_workers(tree, 4)
    assert out["p"].shape == (4, 2, 3)
    np.testing.assert_array_equal(np.asarray(out["p"][2]), np.asarray(tree["p"]))


def test_mix_power_applies_eps_sweeps(devices):
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    w = mm.matrices[0]
    tree = shard_worker_tree(_tree(8), mesh)
    out = mix_power(tree, w, eps=3)
    w3 = np.linalg.matrix_power(w, 3)
    want = _np_mix(w3, tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), want[k], rtol=2e-4, atol=1e-5)


def test_mix_dense_comm_compression_bf16(devices):
    # bf16 on-the-wire mixing approximates the f32 result within bf16
    # tolerance and preserves the leaf dtype.
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    tree = shard_worker_tree(_tree(8), mesh)
    exact = mix_dense(tree, mm.matrices[0], mesh)
    comp = mix_dense(tree, mm.matrices[0], mesh, comm_dtype=jnp.bfloat16)
    for k in tree:
        assert comp[k].dtype == tree[k].dtype
        np.testing.assert_allclose(np.asarray(comp[k]), np.asarray(exact[k]),
                                   atol=0.03, rtol=0.03)


def test_mix_shifts_comm_compression_bf16(devices):
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    shifts = shift_decomposition(mm.matrices[0])
    tree = shard_worker_tree(_tree(8), mesh)
    exact = mix_shifts_shardmap(tree, shifts, mesh)
    comp = mix_shifts_shardmap(tree, shifts, mesh, comm_dtype=jnp.bfloat16)
    for k in tree:
        assert comp[k].dtype == tree[k].dtype
        np.testing.assert_allclose(np.asarray(comp[k]), np.asarray(exact[k]),
                                   atol=0.03, rtol=0.03)


def test_mix_dense_comm_compression_hybrid_mesh(devices):
    # Wire-only compression must also work on the 2-D (hosts x ici)
    # hybrid mesh — the all_gather runs over the worker-axis tuple.
    from dopt.parallel.multihost import make_hybrid_mesh

    mesh = make_hybrid_mesh(2)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    tree = shard_worker_tree(_tree(8), mesh)
    exact = mix_dense(tree, mm.matrices[0], mesh)
    comp = mix_dense(tree, mm.matrices[0], mesh, comm_dtype=jnp.bfloat16)
    for k in tree:
        np.testing.assert_allclose(np.asarray(comp[k]), np.asarray(exact[k]),
                                   atol=0.02, rtol=0.02)


def test_mix_dense_comm_compression_requires_mesh(devices):
    tree = _tree(8)
    with pytest.raises(ValueError, match="requires a mesh"):
        mix_dense(tree, np.eye(8, dtype=np.float32), None,
                  comm_dtype=jnp.bfloat16)


def test_masked_average_comm_compression(devices):
    mesh = make_mesh(8)
    tree = shard_worker_tree(_tree(8), mesh)
    mask = np.array([1, 0, 1, 1, 0, 1, 1, 1], np.float32)
    exact = masked_average(tree, mask)
    comp = jax.jit(
        lambda t: masked_average(t, mask, mesh=mesh, comm_dtype=jnp.bfloat16)
    )(tree)
    for k in tree:
        assert comp[k].shape == tree[k].shape[1:]
        np.testing.assert_allclose(np.asarray(comp[k]), np.asarray(exact[k]),
                                   atol=0.02, rtol=0.02)
    with pytest.raises(ValueError, match="requires a mesh"):
        masked_average(_tree(8), mask, comm_dtype=jnp.bfloat16)
