"""Per-round on-device convergence diagnostics + device-resource
telemetry (``diagnostics="on"``, dopt.config).

Engine legs are tier-1-lean per the tier-1 budget: mlp, tiny synthetic
data, 4 rounds, module-scoped fixtures shared across asserts.  The
cross-path matrix pinned here: per-round vs fused-blocked vs prefetched
vs killed-and-resumed execution of the same config emit canonically
IDENTICAL event streams *including* the new diagnostic gauges — the
PR 8/10 canonical-stream guarantee extended to the diagnostics layer —
while the non-deterministic ``resource``/``compile`` kinds stay outside
the comparison (sampling cadence is an execution-path property).

Everything else (rule state machines, event schema, the profiling
helpers, ledger dedupe, watch rendering) is host-only and synthetic.
"""

from __future__ import annotations

import dataclasses
import json

import pytest

jax = pytest.importorskip("jax")

from dopt.config import (DataConfig, ExperimentConfig, FederatedConfig,
                         GossipConfig, ModelConfig, OptimizerConfig,
                         PopulationConfig)
from dopt.obs import (MemorySink, PrometheusSink, Telemetry, attach,
                      canonical, check_stream, make_event, validate_event)
from dopt.obs.events import DETERMINISTIC_KINDS, DIAG_GAUGES, KINDS
from dopt.obs.rules import (GradExplosionRule, HbmGrowthRule,
                            RetraceStormRule, RunContext, default_rules)
from dopt.utils.profiling import CompileWatcher, device_memory_stats

_DATA = DataConfig(dataset="synthetic", num_users=8, iid=True,
                   synthetic_train_size=128, synthetic_test_size=32)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5)
_ROUNDS = 4

# The six per-round convergence gauges each engine emits: the shared
# five (events.DIAG_GAUGES packed order) + its dispersion meter.
_GOSSIP_DIAG = set(DIAG_GAUGES) | {"consensus_distance"}
_FED_DIAG = set(DIAG_GAUGES) | {"lane_dispersion"}


def _gossip_cfg(**gossip_kw) -> ExperimentConfig:
    kw = dict(algorithm="dsgd", topology="circle", mode="metropolis",
              rounds=_ROUNDS, local_ep=1, local_bs=32, diagnostics="on")
    kw.update(gossip_kw)
    return ExperimentConfig(name="diag-gossip", seed=7, data=_DATA,
                            model=_MODEL, optim=_OPTIM,
                            gossip=GossipConfig(**kw))


def _fed_cfg(**fed_kw) -> ExperimentConfig:
    kw = dict(algorithm="fedavg", frac=0.5, rounds=_ROUNDS, local_ep=1,
              local_bs=32, diagnostics="on")
    kw.update(fed_kw)
    return ExperimentConfig(name="diag-fed", seed=7, data=_DATA,
                            model=_MODEL, optim=_OPTIM,
                            federated=FederatedConfig(**kw))


def _trainer(cfg: ExperimentConfig):
    if cfg.federated is not None:
        from dopt.engine.federated import FederatedTrainer

        return FederatedTrainer(cfg)
    from dopt.engine.gossip import GossipTrainer

    return GossipTrainer(cfg)


def _run(cfg: ExperimentConfig, *, per_round: bool = False):
    tr = _trainer(cfg)
    mem = MemorySink()
    attach(tr, Telemetry([mem]), fresh=True)
    if per_round:
        for _ in range(_ROUNDS):
            tr.run(rounds=1)
    else:
        tr.run(rounds=_ROUNDS)
    return tr, mem.events


@pytest.fixture(scope="module")
def gossip_on():
    """Blocked gossip run with diagnostics on — the reference stream."""
    tr, events = _run(_gossip_cfg())
    return tr.history, events


@pytest.fixture(scope="module")
def fed_on():
    tr, events = _run(_fed_cfg())
    return tr.history, events


# ------------------------------------------------- cross-path equality
def _round_gauges(events) -> dict[int, set]:
    by_round: dict[int, set] = {}
    for e in events:
        if e["kind"] == "gauge":
            by_round.setdefault(int(e["round"]), set()).add(e["name"])
    return by_round


def test_gossip_diag_stream(gossip_on):
    _, stream = gossip_on
    s = check_stream(stream)
    assert s["rounds"] == _ROUNDS
    # EVERY round bundle carries all six convergence gauges.
    for t, names in _round_gauges(stream).items():
        assert _GOSSIP_DIAG <= names, (t, names)
    # The resource channel sampled at least once; round fns compiled.
    assert s["kinds"].get("resource", 0) >= 1
    assert s["kinds"].get("compile", 0) >= 1
    # The end-of-run consensus gauge is SUPPRESSED (the diag block
    # carries a true per-round one): exactly one per round, no extra.
    cds = [e for e in stream if e["kind"] == "gauge"
           and e["name"] == "consensus_distance"]
    assert len(cds) == _ROUNDS

    _, per = _run(_gossip_cfg(), per_round=True)
    assert canonical(per) == canonical(stream)


def test_fed_diag_stream(fed_on):
    _, stream = fed_on
    s = check_stream(stream)
    assert s["rounds"] == _ROUNDS
    for t, names in _round_gauges(stream).items():
        assert _FED_DIAG <= names, (t, names)
    assert s["kinds"].get("resource", 0) >= 1
    assert s["kinds"].get("compile", 0) >= 1

    _, per = _run(_fed_cfg(), per_round=True)
    assert canonical(per) == canonical(stream)


def test_prefetch_stream_equality(gossip_on, fed_on):
    _, g_stream = gossip_on
    _, g_pf = _run(_gossip_cfg(prefetch="on"))
    assert canonical(g_pf) == canonical(g_stream)
    _, f_stream = fed_on
    _, f_pf = _run(_fed_cfg(prefetch="on"))
    assert canonical(f_pf) == canonical(f_stream)


def test_kill_resume_stream_equality(fed_on, tmp_path):
    """Killed-and-resumed equality WITH gauges included — stronger than
    the PR 8 round+fault assert, enabled by suppressing the
    per-``run()``-call end-of-run consensus gauge under diagnostics."""
    from dopt.obs import JsonlSink

    _, stream = fed_on
    mpath = tmp_path / "m.jsonl"
    ck = tmp_path / "ck"
    kill_at = _ROUNDS // 2

    part = _trainer(_fed_cfg())
    t1 = Telemetry.to_jsonl(mpath)
    attach(part, t1)
    part.run(rounds=kill_at, checkpoint_every=1, checkpoint_path=ck)
    t1.close()

    res = _trainer(_fed_cfg())
    res.restore(ck)
    t2 = Telemetry.to_jsonl(mpath, resume=True)
    attach(res, t2)
    res.run(rounds=_ROUNDS - res.round)
    t2.close()

    merged = JsonlSink.read(mpath)
    check_stream(merged)
    assert canonical(merged) == canonical(stream)   # gauges included


def test_diag_training_math_unperturbed(gossip_on):
    """diagnostics="on" observes; it must not change what trains: the
    History a diagnosed run produces matches the diagnostics-off run's
    (same schema, values equal up to XLA refusion noise — the extra
    diag reductions change op fusion, so the last float bits may
    wiggle; anything past ~1e-5 relative would be a real feedback
    path)."""
    h_on, _ = gossip_on
    off = _trainer(_gossip_cfg(diagnostics="off"))
    h_off = off.run(rounds=_ROUNDS)
    assert len(h_off.rows) == len(h_on.rows)
    for a, b in zip(h_on.rows, h_off.rows):
        assert a.keys() == b.keys()
        for k in a:
            if isinstance(a[k], float):
                assert a[k] == pytest.approx(b[k], rel=1e-5, abs=1e-7), k
            else:
                assert a[k] == b[k], k


# -------------------------------------------------------- config gates
def test_bad_diagnostics_value_rejected():
    with pytest.raises(ValueError, match="diagnostics"):
        _trainer(_gossip_cfg(diagnostics="sometimes"))
    with pytest.raises(ValueError, match="diagnostics"):
        _trainer(_fed_cfg(diagnostics="sometimes"))


def test_population_mode_rejected():
    cfg = dataclasses.replace(
        _fed_cfg(), population=PopulationConfig(clients=32, cohort=16))
    with pytest.raises(ValueError, match="population"):
        _trainer(cfg)
    gcfg = dataclasses.replace(
        _gossip_cfg(), population=PopulationConfig(clients=32, cohort=16))
    with pytest.raises(ValueError, match="population"):
        _trainer(gcfg)


# ------------------------------------------------------- event schema
def test_resource_compile_kinds_registered():
    assert "resource" in KINDS and "compile" in KINDS
    # Sampling cadence is an execution-path property: both kinds stay
    # OUTSIDE the canonical-stream comparison.
    assert "resource" not in DETERMINISTIC_KINDS
    assert "compile" not in DETERMINISTIC_KINDS


def test_resource_compile_events_validate():
    validate_event(make_event("resource", round=3, engine="gossip",
                              live_bytes=1 << 20, peak_bytes=2 << 20,
                              source="host_rss"))
    validate_event(make_event("resource", round=0, peak_bytes=0))
    validate_event(make_event("compile", round=0, fn="round_fn",
                              count=1, total=2, seconds=0.5))


@pytest.mark.parametrize("bad", [
    {"v": 1, "kind": "resource", "ts": 0.0, "round": 0},  # no peak_bytes
    {"v": 1, "kind": "resource", "ts": 0.0, "round": 0,
     "peak_bytes": -1},                                   # negative
    {"v": 1, "kind": "resource", "ts": 0.0, "round": 0,
     "peak_bytes": float("inf")},                         # non-finite
    {"v": 1, "kind": "compile", "ts": 0.0, "round": 0,
     "count": 1, "seconds": 0.1},                         # missing fn
    {"v": 1, "kind": "compile", "ts": 0.0, "round": 0, "fn": "f",
     "count": 0, "seconds": 0.1},                         # count < 1
    {"v": 1, "kind": "compile", "ts": 0.0, "round": 0, "fn": "f",
     "count": 1, "seconds": float("nan")},                # non-finite s
])
def test_malformed_resource_compile_rejected(bad):
    with pytest.raises(ValueError):
        validate_event(bad)


# ------------------------------------------------------------- rules
def _gauge(t, name, value):
    return make_event("gauge", round=t, name=name, value=value)


def test_grad_explosion_rule_edge_and_per_gauge_windows():
    r = GradExplosionRule(window=8, factor=10.0, min_delta=1.0,
                          min_history=3)
    ctx = RunContext()
    fired = []
    for t in range(4):          # below min_history then steady
        fired += r.update(_gauge(t, "grad_norm", 1.0), ctx)
    assert not fired
    fired = r.update(_gauge(4, "grad_norm", 50.0), ctx)   # 10x1+1 < 50
    assert len(fired) == 1 and "grad_norm" in fired[0]["message"]
    # Edge-triggered: the episode fires once...
    assert not r.update(_gauge(5, "grad_norm", 60.0), ctx)
    # ...re-arms when the condition clears (median has crept up), and
    # update_norm keeps its OWN window: no cross-gauge contamination.
    for t in range(6, 10):
        r.update(_gauge(t, "grad_norm", 1.0), ctx)
    for t in range(10, 13):
        assert not r.update(_gauge(t, "update_norm", 1.0), ctx)
    assert r.update(_gauge(13, "update_norm", 100.0), ctx)
    # Other gauges pass through untouched.
    assert not r.update(_gauge(14, "lane_loss_mean", 1e9), ctx)


def test_retrace_storm_rule():
    r = RetraceStormRule(window=8, max_rounds=3)
    ctx = RunContext()

    def compile_ev(t):
        return make_event("compile", round=t, fn="round_fn", count=1,
                          total=t + 1, seconds=0.1)

    # Warmup compiles at 2 distinct rounds: healthy, silent.
    assert not r.update(compile_ev(0), ctx)
    assert not r.update(compile_ev(0), ctx)
    assert not r.update(compile_ev(1), ctx)
    assert not r.update(compile_ev(2), ctx)     # 3 distinct = at limit
    fired = r.update(compile_ev(3), ctx)        # 4th distinct round
    assert len(fired) == 1 and fired[0]["value"] == 4.0
    # Old rounds age out of the window; the rule re-arms.
    assert not r.update(compile_ev(20), ctx)


def test_hbm_growth_rule():
    r = HbmGrowthRule(patience=4, tol=0.5, min_bytes=64 << 20)
    ctx = RunContext()

    def res(t, live):
        return make_event("resource", round=t, live_bytes=live,
                          peak_bytes=live)

    g = 1 << 30
    # Plateau: silent.
    for t in range(6):
        assert not r.update(res(t, g), ctx)
    # Strictly-rising but under both margins: silent.
    for t in range(6, 11):
        assert not r.update(res(t, g + (t << 10)), ctx)
    # The leak shape: 5 consecutive strictly-rising samples, +50% rel
    # AND +64MiB abs.
    fired = []
    for i, t in enumerate(range(11, 16)):
        fired += r.update(res(t, g + i * (300 << 20)), ctx)
    assert len(fired) == 1
    # Falls back to peak_bytes when live_bytes is absent; non-numeric
    # samples are ignored, not crashed on.
    assert not r.update({"v": 1, "kind": "resource", "ts": 0.0,
                         "round": 16, "peak_bytes": g}, ctx)
    assert not r.update({"v": 1, "kind": "resource", "ts": 0.0,
                         "round": 17}, ctx)


def test_new_rules_in_default_set():
    names = {r.name for r in default_rules()}
    assert {"grad_explosion", "retrace_storm", "hbm_growth"} <= names


# -------------------------------------------------- profiling helpers
def test_device_memory_stats_finite():
    mem = device_memory_stats()
    assert mem is not None
    assert mem["source"] in ("device", "host_rss")
    assert isinstance(mem["peak_bytes"], int) and mem["peak_bytes"] > 0
    assert isinstance(mem["live_bytes"], int) and mem["live_bytes"] > 0


def test_compile_watcher():
    class _Fn:
        def __init__(self):
            self.n = 0

        def _cache_size(self):
            return self.n

    fn = _Fn()
    w = CompileWatcher()
    assert w.observe("f", fn) is None          # empty cache: no signal
    fn.n = 1
    assert w.observe("f", fn) == {"count": 1, "total": 1}   # warmup
    assert w.observe("f", fn) is None          # stable: no retrace
    fn.n = 3
    assert w.observe("f", fn) == {"count": 2, "total": 3}   # retraced
    # Wrappers without a cache probe degrade to silence, not a crash.
    assert w.observe("g", object()) is None


# --------------------------------------------------- ledger dedupe
def test_bench_ledger_dedupes_on_run_id(tmp_path):
    from dopt.obs.regress import append_entry, read_ledger

    path = tmp_path / "bench_history.jsonl"
    append_entry(path, {"metric": "m", "value": 1.0}, run_id="r1", sha="s")
    append_entry(path, {"metric": "m", "value": 2.0}, run_id="r2", sha="s")
    # Re-run at r1 REPLACES the stale entry instead of stacking a
    # duplicate that would skew the trailing trimmed median.
    append_entry(path, {"metric": "m", "value": 9.0}, run_id="r1", sha="s")
    entries = read_ledger(path)
    assert [e["run_id"] for e in entries] == ["r2", "r1"]
    assert entries[-1]["bench"]["value"] == 9.0
    assert len(path.read_text().splitlines()) == 2


def test_bench_ledger_keeps_multiple_metrics_per_run(tmp_path):
    """One real bench run appends SEVERAL metric lines (the gossip
    headline plus the seqlm leg) under the shared run id — the dedup
    key is (run_id, metric), so the second append must not swallow the
    first, while a re-run of the same metric still replaces it."""
    from dopt.obs.regress import append_entry, read_ledger

    path = tmp_path / "bench_history.jsonl"
    append_entry(path, {"metric": "gossip", "value": 2.0},
                 run_id="r7", sha="s")
    append_entry(path, {"metric": "seqlm", "value": 900.0},
                 run_id="r7", sha="s")
    entries = read_ledger(path)
    assert [(e["run_id"], e["bench"]["metric"]) for e in entries] == [
        ("r7", "gossip"), ("r7", "seqlm")]
    # Same (run_id, metric) slot replaces; the sibling metric survives.
    append_entry(path, {"metric": "seqlm", "value": 950.0},
                 run_id="r7", sha="s")
    entries = read_ledger(path)
    assert len(entries) == 2
    assert entries[-1]["bench"]["value"] == 950.0
    assert entries[0]["bench"]["metric"] == "gossip"


def test_bench_ledger_append_survives_torn_line(tmp_path):
    """The plain-append path is not atomic, so a crash can tear the
    final line; the next append must not raise, must not glue its entry
    onto the garbage, and must REPAIR the ledger (drop the torn line)
    so the strict read_ledger / regressor CLI keeps working."""
    from dopt.obs.regress import append_entry, read_ledger

    path = tmp_path / "bench_history.jsonl"
    append_entry(path, {"metric": "m", "value": 1.0}, run_id="r1", sha="s")
    with open(path, "a") as f:
        f.write('{"bench": {"metric": "m", "va')  # torn mid-write
    append_entry(path, {"metric": "m", "value": 2.0}, run_id="r2", sha="s")
    entries = read_ledger(path)  # strict read works again
    assert [e["run_id"] for e in entries] == ["r1", "r2"]
    assert len(path.read_text().splitlines()) == 2


def test_consensus_stall_reads_lane_dispersion():
    """The federated engine's diagnostics dispersion meter is named
    lane_dispersion; the stall rule must consume it — otherwise
    diagnostics='on' (which suppresses the end-of-run
    consensus_distance gauge) would disable stall monitoring there."""
    from dopt.obs.rules import ConsensusStallRule

    ctx = RunContext()
    r = ConsensusStallRule(patience=3, tol=0.25)
    fired = []
    for t, v in enumerate([1.0, 1.5, 2.0, 3.0]):
        fired += r.update(make_event("gauge", round=t,
                                     name="lane_dispersion", value=v), ctx)
    assert len(fired) == 1 and fired[0]["round"] == 3


# ------------------------------------------------------------- watch
def test_watch_renders_all_gauges_and_memory(tmp_path):
    from dopt.obs.monitor import HealthMonitor
    from dopt.obs.watch import WatchState

    events = [
        make_event("run", engine="gossip", name="x", round=0, workers=8),
        make_event("round", round=0, engine="gossip",
                   metrics={"avg_train_loss": 0.5}),
        _gauge(0, "update_norm", 1.25),
        _gauge(0, "consensus_distance", 0.5),
        _gauge(0, "some_future_gauge", 3.0),
        make_event("resource", round=0, engine="gossip",
                   live_bytes=1 << 30, peak_bytes=2 << 30,
                   source="host_rss"),
        make_event("compile", round=0, fn="round_fn", count=1,
                   seconds=0.2),
    ]
    mpath = tmp_path / "m.jsonl"
    mpath.write_text("".join(json.dumps(e) + "\n" for e in events))

    state = WatchState(HealthMonitor())
    state.poll(mpath)
    out = state.render()
    # No whitelist: every gauge in the stream renders, unknown ones
    # included — new diagnostic gauges surface without a code edit.
    for name in ("update_norm", "consensus_distance",
                 "some_future_gauge"):
        assert name in out
    assert "peak=2.00GiB" in out and "live=1.00GiB" in out
    assert "compiles=1" in out

    filt = WatchState(HealthMonitor(), gauge_filter={"update_norm"})
    filt.poll(mpath)
    out = filt.render()
    assert "update_norm" in out and "some_future_gauge" not in out


def test_prometheus_resource_and_compile_families():
    sink = PrometheusSink()
    sink.emit(make_event("resource", round=0, engine="gossip",
                         live_bytes=100, peak_bytes=200,
                         source="host_rss"))
    sink.emit(make_event("compile", round=0, fn="round_fn", count=2,
                         seconds=0.1))
    sink.emit(make_event("compile", round=1, fn="round_fn", count=1,
                         seconds=0.1))
    text = sink.render()
    assert 'dopt_hbm_live_bytes{engine_kind="gossip"} 100.0' in text
    assert 'dopt_hbm_peak_bytes{engine_kind="gossip"} 200.0' in text
    assert 'dopt_compiles_total{fn="round_fn"} 3' in text
