"""Model zoo in flax.linen (TPU compute path).

Re-creates the reference's two CNNs with exact parameter-count parity
(``models.py`` in both reference projects — `Model1`: 1,663,370 params
for MNIST/FMNIST, `Model3`: 1,105,098 for CIFAR-10) and adds the models
the benchmark configs need: an MLP, ℓ2-regularised logistic regression
(a9a / ADMM), and a GroupNorm ResNet-18 for the 32-worker CIFAR-10
north-star config.

Faithful-head semantics: the reference ends both CNNs in ``nn.Softmax``
*and* trains with ``CrossEntropyLoss`` (which applies log_softmax
internally) — a double softmax (SURVEY §3.4).  ``faithful=True``
reproduces that: ``__call__`` returns *probabilities* and the loss in
``dopt.models.losses`` applies log_softmax on top, bit-matching the
reference's objective.  ``faithful=False`` returns logits (the
corrected, idiomatic head).

Data layout is NHWC (TPU-native).  The reference flattens NCHW
channel-major before its first Dense layer; parameter-conversion
helpers in ``dopt.engine.oracle`` handle that reordering so torch and
flax foward passes are comparable element-wise.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _head(x: jnp.ndarray, faithful: bool) -> jnp.ndarray:
    """Output head: softmax probabilities in faithful mode (the
    reference's double-softmax objective), logits otherwise."""
    if faithful:
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)
    return x


def _max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """2×2 stride-2 max pool via reshape + reduce_max.

    Forward-identical to ``nn.max_pool(x, (2, 2), strides=(2, 2))`` for
    even H/W (the windows are non-overlapping, so the reshape tiles them
    exactly), but its VJP lowers to an elementwise equality-mask instead
    of XLA's ``select_and_scatter`` — which the reduce_window backward
    otherwise costs us ~12% of device time on the Model1 training step
    (results/trace_headline.json).  Tie handling differs in theory
    (gradient splits equally across tied window elements rather than
    picking the first winner); on float conv activations ties are
    measure-zero and the oracle parity suite stays green.

    Odd spatial dims fall back to ``nn.max_pool`` (which floors), since
    the reshape tiling requires even H/W.
    """
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        return nn.max_pool(x, (2, 2), strides=(2, 2))
    return x.reshape(b, h // 2, 2, w // 2, 2, c).max(axis=(2, 4))


class _ReferenceCNN(nn.Module):
    """Shared body of the reference's two CNNs (``models.py`` both
    projects): conv(·→32,k5,SAME) → maxpool2 → conv(32→64,k5,SAME) →
    maxpool2 → Dense(hidden) → ReLU → Dense(num_classes) [→ Softmax].
    They differ only in the first Dense width.

    Faithful quirk: the reference conv stack has NO activations — the
    only ReLU sits between the two Dense layers (models.py:10-21).  Two
    stacked linear convs are a strictly weaker function class, but that
    is the architecture the published numbers used; ``faithful=True``
    reproduces it exactly, ``faithful=False`` adds the conventional
    post-conv ReLUs (and drops the softmax head)."""

    hidden: int = 512
    num_classes: int = 10
    faithful: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        if not self.faithful:
            x = nn.relu(x)
        x = _max_pool_2x2(x)
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        if not self.faithful:
            x = nn.relu(x)
        x = _max_pool_2x2(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        return _head(x, self.faithful)


class Model1(_ReferenceCNN):
    """MNIST/FMNIST CNN (reference ``models.py:6-27``), 1,663,370 params."""

    hidden: int = 512


class Model3(_ReferenceCNN):
    """CIFAR CNN (reference ``models.py:31-51``), 1,105,098 params @ 10 classes."""

    hidden: int = 256


class MLP(nn.Module):
    """Small MLP (BASELINE.json config 1: 4-worker MNIST MLP)."""

    hidden: Sequence[int] = (200, 200)
    num_classes: int = 10
    faithful: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, dtype=self.dtype, name=f"fc{i+1}")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return _head(x, self.faithful)


class LogisticRegression(nn.Module):
    """ℓ2-regularised logistic regression (BASELINE.json config 4:
    16-worker ADMM on a9a).  The ℓ2 term lives in the loss, not here."""

    num_classes: int = 2
    faithful: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="linear")(x)
        return _head(x, self.faithful)


class ResidualBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.features))(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    """CIFAR-style ResNet-18 with GroupNorm (BASELINE.json config 5:
    32-worker gossip SGD, CIFAR-10, time-varying random graphs).

    GroupNorm instead of BatchNorm: batch statistics are ill-defined
    under federated/gossip averaging (each worker's running stats
    diverge and averaging them is not principled), and GN keeps the
    model a pure function of (params, batch) — no mutable state to
    thread through the stacked-worker engine.  Standard choice in the
    FL literature.
    """

    num_classes: int = 10
    faithful: bool = False
    dtype: Any = jnp.float32
    stage_sizes: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=32)(x)
        x = nn.relu(x)
        for stage, blocks in enumerate(self.stage_sizes):
            features = 64 * (2 ** stage)
            for b in range(blocks):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = ResidualBlock(features, strides=strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return _head(x, self.faithful)


class TransformerLM(nn.Module):
    """Decoder-only transformer LM — the long-context member of the zoo.

    Nothing like it exists in the reference (no attention, no sequence
    axis anywhere — SURVEY §2.3); this is the framework's own
    demonstration that its sequence-parallel substrate
    (``dopt.parallel.sequence``) plugs into a real model.  ``attn_fn``
    injects the attention implementation: ``None`` uses single-device
    dense attention; pass ``lambda q,k,v: ring_attention(q,k,v,mesh,
    causal=True)`` (or the Ulysses variant) to shard the sequence axis
    over a mesh with NO other change to the model.

    Pre-LN blocks, learned positional embeddings, weight-tied output
    head.  Call input: [B, L] int32 tokens; output [B, L, vocab]
    logits (``num_classes`` is the vocab size).
    """

    num_classes: int = 256          # vocab
    faithful: bool = False          # kept for zoo-interface uniformity
    dtype: Any = jnp.float32
    dim: int = 128
    depth: int = 2
    heads: int = 4
    max_len: int = 2048

    @nn.compact
    def __call__(self, tokens, attn_fn=None):
        from dopt.parallel.sequence import dense_attention

        attn = attn_fn or (lambda q, k, v: dense_attention(q, k, v,
                                                           causal=True))
        b, l = tokens.shape
        if l > self.max_len:
            raise ValueError(f"sequence length {l} > max_len {self.max_len}")
        if self.dim % self.heads:
            raise ValueError(f"dim {self.dim} not divisible by "
                             f"heads {self.heads}")
        emb = nn.Embed(self.num_classes, self.dim, dtype=self.dtype,
                       name="tok_emb")
        x = emb(tokens)
        x = x + self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (self.max_len, self.dim))[None, :l].astype(self.dtype)
        hd = self.dim // self.heads
        for i in range(self.depth):
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln1_{i}")(x)
            qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                           name=f"qkv_{i}")(y)
            q, k, v = jnp.split(qkv.reshape(b, l, 3 * self.heads, hd), 3,
                                axis=2)
            o = attn(q, k, v).reshape(b, l, self.dim)
            x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                             name=f"proj_{i}")(o)
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln2_{i}")(x)
            y = nn.Dense(4 * self.dim, dtype=self.dtype, name=f"up_{i}")(y)
            y = nn.gelu(y)
            x = x + nn.Dense(self.dim, dtype=self.dtype, name=f"down_{i}")(y)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = x @ emb.embedding.T.astype(self.dtype)
        return _head(logits, self.faithful)


def resolve_stacked_apply(model, stacked_impl: str):
    """Validate ``ModelConfig.stacked_impl`` and resolve the grouped
    stacked forward for it — the one shared entry point both engines
    use, so the accepted values can never drift between them."""
    if stacked_impl not in ("auto", "vmap"):
        raise ValueError(
            f"unknown stacked_impl {stacked_impl!r}; one of auto|vmap")
    return make_stacked_apply(model) if stacked_impl == "auto" else None


def make_stacked_apply(model) -> "callable | None":
    """Stacked-worker forward for the reference CNNs as ONE grouped-conv
    program — the engine's fast path around ``vmap(model.apply)``.

    XLA lowers a conv vmapped over per-worker kernels poorly on TPU
    (layout shuffles around every conv; measured 1.6× step slowdown at
    6 workers and ~4× at 32).  The same math maps exactly onto a single
    ``conv_general_dilated`` with ``feature_group_count=W``: put the
    worker axis into the channel dimension ([W, B, H, Wd, C] →
    [B, H, Wd, W·C]) and concatenate the per-worker kernels into
    [kh, kw, C, W·Cout] — group w then convolves worker w's channels
    with worker w's kernel, which is precisely the stacked-fleet
    forward.  The FC layers stay batched einsums (MXU-native under
    batching).  Prototype measurement: 0.43 ms vs 1.43 ms per fused
    train step on the headline workload (v5e).

    Returns ``apply(stacked_params, x)`` mapping a [W, ...]-stacked
    param pytree (the engine's native layout) and [W, B, H, Wd, C]
    inputs to [W, B, num_classes] outputs — bit-comparable to
    ``vmap(model.apply)`` up to float reassociation inside the conv —
    or ``None`` for models without a grouped-stacked form (the engines
    fall back to vmap).
    """
    if not isinstance(model, _ReferenceCNN):
        return None
    faithful, dtype = model.faithful, model.dtype

    def conv_grouped(z, kernel, bias, groups, padding="SAME"):
        """z [B, H, Wd, G·Cin], kernel [G, kh, kw, Cin, Cout]."""
        g_kernel = jnp.moveaxis(kernel.astype(dtype), 0, 3)
        g_kernel = g_kernel.reshape(*g_kernel.shape[:3], -1)  # [kh,kw,Cin,G·Cout]
        out = jax.lax.conv_general_dilated(
            z, g_kernel, (1, 1), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)
        return out + bias.astype(dtype).reshape(1, 1, 1, -1)

    def apply(params, x):
        w, b = x.shape[0], x.shape[1]
        # [W, B, H, Wd, C] → [B, H, Wd, W·C] (worker-major channels)
        z = jnp.moveaxis(x.astype(dtype), 0, 3)
        z = z.reshape(*z.shape[:3], -1)
        c1, c2 = params["conv1"], params["conv2"]
        z = conv_grouped(z, c1["kernel"], c1["bias"], w)
        if not faithful:
            z = nn.relu(z)
        z = _max_pool_2x2(z)
        z = conv_grouped(z, c2["kernel"], c2["bias"], w)
        if not faithful:
            z = nn.relu(z)
        z = _max_pool_2x2(z)          # [B, H', Wd', W·C2]
        h_, wd_ = z.shape[1], z.shape[2]
        c2n = z.shape[3] // w
        # The FC layers stay grouped convs too — a Dense over the
        # flattened [H', Wd', C2] is exactly a VALID H'×Wd' conv, and
        # keeping the worker axis in channels end-to-end avoids a
        # [W·B·3136] activation relayout between conv and FC whose
        # forward+backward transposes alone cost ~2× the conv time in
        # the einsum formulation (measured on v5e).
        f1, f2 = params["fc1"], params["fc2"]
        hidden = f1["kernel"].shape[-1]
        # flax flattens [H', Wd', C2] row-major, so [W, H'·Wd'·C2, O]
        # reshapes to [W, H', Wd', C2, O] with matching index order.
        f1k = f1["kernel"].reshape(w, h_, wd_, c2n, hidden)
        z = conv_grouped(z, f1k, f1["bias"], w, "VALID")  # [B, 1, 1, W·hidden]
        z = nn.relu(z)
        ncls = f2["kernel"].shape[-1]
        f2k = f2["kernel"].reshape(w, 1, 1, hidden, ncls)
        z = conv_grouped(z, f2k, f2["bias"], w, "VALID")  # [B, 1, 1, W·ncls]
        z = z.reshape(b, w, ncls)
        z = jnp.moveaxis(z, 1, 0)                 # [W, B, ncls]
        return _head(z, faithful)

    return apply


_ZOO = {
    "model1": Model1,
    "model3": Model3,
    "mlp": MLP,
    "logistic": LogisticRegression,
    "resnet18": ResNet18,
    "transformer": TransformerLM,
}


def build_model(
    name: str,
    *,
    num_classes: int = 10,
    faithful: bool | None = None,
    dtype: Any = jnp.float32,
) -> nn.Module:
    """Model dispatch by name — the typed replacement for the reference's
    if/elif on ``args.model`` (``servers.py:33-40``, ``simulators.py:31-38``).

    ``faithful=None`` keeps each model's own default: True only for
    the two reference CNNs (which have a double-softmax to be faithful
    to), False for mlp/logistic/resnet18 (new models, corrected head).
    ``dtype`` may be a string ("bfloat16" → MXU-native compute); params
    stay float32 (flax param_dtype default) — bf16 is compute-only.
    """
    if isinstance(dtype, str):
        dtype = jnp.dtype(dtype)
    key = name.lower()
    if key not in _ZOO:
        raise ValueError(f"unknown model {name!r}; one of {sorted(_ZOO)}")
    kwargs: dict[str, Any] = dict(num_classes=num_classes, dtype=dtype)
    if faithful is not None:
        kwargs["faithful"] = faithful
    return _ZOO[key](**kwargs)


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
