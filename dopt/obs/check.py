"""Telemetry stream checker: ``python -m dopt.obs.check metrics.jsonl``.

Validates every event against the versioned schema (dopt.obs.events)
and enforces the continuity invariant — within each ``run`` segment the
round sequence is gapless and duplicate-free — then prints a one-line
summary per file.  Exit code 1 on the first violation, so CI can gate
on the artifact it just produced.  Stdlib-only (no jax import).
"""

from __future__ import annotations

import argparse
import sys
from typing import Any

from dopt.obs.events import check_stream
from dopt.obs.sinks import JsonlSink


def check_file(path: str) -> dict[str, Any]:
    """Validate one JSONL stream file; returns the check_stream summary
    (raises ValueError on schema or continuity violations)."""
    events = JsonlSink.read(path)
    if not events:
        raise ValueError(f"{path}: empty telemetry stream")
    return check_stream(events)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+", metavar="METRICS_JSONL")
    args = ap.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            s = check_file(path)
        except (OSError, ValueError) as e:
            print(f"{path}: FAIL {e}", file=sys.stderr)
            rc = 1
            continue
        kinds = " ".join(f"{k}={v}" for k, v in sorted(s["kinds"].items()))
        print(f"{path}: ok — {s['events']} events, {s['rounds']} rounds, "
              f"{s['segments']} segment(s) [{kinds}]")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
