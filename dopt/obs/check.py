"""Telemetry stream checker: ``python -m dopt.obs.check metrics.jsonl``.

Validates every event against the versioned schema (dopt.obs.events)
and enforces the continuity invariant — within each ``run`` segment the
round sequence is gapless and duplicate-free — then prints a one-line
summary per file.  ``--summary`` additionally prints a per-file
inventory (per-kind event counts, round span per segment, gauge key
inventory, alert rules fired) — the eyeball view of a 10k-round stream
the pass/fail line can't give.  Stdlib-only (no jax import).

Exit codes follow the shared ``dopt.analysis`` convention: 0 every
stream clean, 1 any violation, 2 usage error (argparse); ``--json``
prints one machine-readable report for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from dopt.obs.events import check_stream
from dopt.obs.sinks import JsonlSink


def check_file(path: str) -> dict[str, Any]:
    """Validate one JSONL stream file; returns the check_stream summary
    (raises ValueError on schema or continuity violations)."""
    events = JsonlSink.read(path)
    if not events:
        raise ValueError(f"{path}: empty telemetry stream")
    return check_stream(events)


def summarize(events: list[dict[str, Any]]) -> dict[str, Any]:
    """Inventory of an (already validated) stream: per-kind counts,
    per-segment round spans, gauge keys (count + last value), round
    metric keys, fault kinds, alert rules."""
    kinds: dict[str, int] = {}
    segments: list[dict[str, Any]] = []
    gauges: dict[str, dict[str, Any]] = {}
    metric_keys: dict[str, int] = {}
    faults: dict[str, int] = {}
    alerts: dict[str, int] = {}
    for ev in events:
        kind = ev.get("kind")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "run":
            segments.append({"engine": ev.get("engine"),
                             "name": ev.get("name"),
                             "start": ev.get("round"),
                             "first": None, "last": None, "rounds": 0})
        elif kind == "round":
            if not segments:
                segments.append({"engine": ev.get("engine"),
                                 "name": None, "start": ev.get("round"),
                                 "first": None, "last": None, "rounds": 0})
            seg = segments[-1]
            t = ev.get("round")
            seg["first"] = t if seg["first"] is None else seg["first"]
            seg["last"] = t
            seg["rounds"] += 1
            for k in ev.get("metrics", {}):
                metric_keys[k] = metric_keys.get(k, 0) + 1
        elif kind == "gauge":
            g = gauges.setdefault(str(ev.get("name")),
                                  {"count": 0, "last": None})
            g["count"] += 1
            g["last"] = ev.get("value")
        elif kind == "fault":
            f = str(ev.get("fault"))
            faults[f] = faults.get(f, 0) + 1
        elif kind == "alert":
            r = str(ev.get("rule"))
            alerts[r] = alerts.get(r, 0) + 1
    return {"kinds": kinds, "segments": segments, "gauges": gauges,
            "metric_keys": metric_keys, "faults": faults, "alerts": alerts}


def print_summary(path: str, inv: dict[str, Any]) -> None:
    print(f"{path}:")
    print("  kinds     " + "  ".join(
        f"{k}={v}" for k, v in sorted(inv["kinds"].items())))
    for i, seg in enumerate(inv["segments"]):
        span = ("-" if seg["first"] is None
                else f"{seg['first']}..{seg['last']}")
        print(f"  segment {i}  {seg['engine'] or '?'}"
              f"/{seg['name'] or '?'} start={seg['start']} "
              f"rounds {span} ({seg['rounds']} events)")
    if inv["metric_keys"]:
        print("  metrics   " + "  ".join(
            f"{k}({v})" for k, v in sorted(inv["metric_keys"].items())))
    for name in sorted(inv["gauges"]):
        g = inv["gauges"][name]
        print(f"  gauge     {name}: {g['count']} obs, last={g['last']:g}")
    if inv["faults"]:
        print("  faults    " + "  ".join(
            f"{k}={v}" for k, v in sorted(inv["faults"].items())))
    if inv["alerts"]:
        print("  alerts    " + "  ".join(
            f"{k}={v}" for k, v in sorted(inv["alerts"].items())))


def fleet_stream_paths(state_dir: str) -> list[str]:
    """Every metrics stream under a serve state dir (or a soak state
    root): the leader's + follower streams named by
    ``dopt.obs.aggregate.fleet_metric_paths`` (ONE definition of the
    fleet's stream layout), applied to the dir itself and one
    directory level down (a soak root holding per-leg state dirs)."""
    from pathlib import Path

    from dopt.obs.aggregate import fleet_metric_paths

    root = Path(state_dir)
    dirs = [root] + (sorted(d for d in root.iterdir() if d.is_dir())
                     if root.is_dir() else [])
    found: list[str] = []
    for d in dirs:
        for _, path in sorted(fleet_metric_paths(d).items()):
            if path.exists():
                found.append(str(path))
    return found


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", metavar="METRICS_JSONL")
    ap.add_argument("--state-dir", default=None, metavar="DIR",
                    help="additionally check every metrics*.jsonl under "
                         "this serve state dir (one level of "
                         "subdirectories included) — one invocation "
                         "validates a whole fleet's streams")
    ap.add_argument("--summary", action="store_true",
                    help="print a per-file inventory (per-kind counts, "
                         "round span per segment, gauge keys, alert "
                         "rules) after validating")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout (the "
                         "dopt.analysis CLI convention)")
    args = ap.parse_args(argv)
    paths = list(args.paths)
    if args.state_dir is not None:
        found = fleet_stream_paths(args.state_dir)
        if not found and not paths:
            print(f"{args.state_dir}: FAIL no metrics*.jsonl streams "
                  "found", file=sys.stderr)
            return 1
        paths.extend(p for p in found if p not in paths)
    if not paths:
        ap.error("give METRICS_JSONL paths and/or --state-dir")
    rc = 0
    report: list[dict[str, Any]] = []
    for path in paths:
        try:
            events = JsonlSink.read(path)
            if not events:
                raise ValueError(f"{path}: empty telemetry stream")
            s = check_stream(events)
        except (OSError, ValueError) as e:
            if args.json:
                report.append({"path": path, "ok": False,
                               "error": str(e)})
            else:
                print(f"{path}: FAIL {e}", file=sys.stderr)
            rc = 1
            continue
        if args.json:
            entry: dict[str, Any] = {"path": path, "ok": True, **s}
            if args.summary:
                entry["summary"] = summarize(events)
            report.append(entry)
            continue
        kinds = " ".join(f"{k}={v}" for k, v in sorted(s["kinds"].items()))
        print(f"{path}: ok — {s['events']} events, {s['rounds']} rounds, "
              f"{s['segments']} segment(s) [{kinds}]")
        if args.summary:
            print_summary(path, summarize(events))
    if args.json:
        json.dump({"tool": "dopt.obs.check", "checked": len(paths),
                   "files": report, "clean": rc == 0},
                  sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
