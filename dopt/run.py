"""CLI experiment runner: ``python -m dopt.run --preset reference-fedavg``.

The typed replacement for the reference's notebook driver cells: pick a
preset (or override fields), run, print per-round metrics, export the
history CSV in the reference's results layout, optionally checkpoint.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import re
import sys


def apply_override(cfg, spec: str):
    """``--set path.to.field=value``: frozen-dataclass field override by
    dotted path.  The value is coerced from the FIELD ANNOTATION (not
    the current value, which may be None), with strict bool parsing and
    clean SystemExit errors for every bad input."""
    path, eq, raw = spec.partition("=")
    if not eq:
        raise SystemExit(f"--set expects PATH=VALUE, got {spec!r}")
    parts = path.split(".")
    objs = [cfg]
    for p in parts[:-1]:
        names = {f.name for f in dataclasses.fields(objs[-1])}
        if p not in names:
            raise SystemExit(f"--set: {path!r} not found on this preset")
        nxt = getattr(objs[-1], p)
        if not dataclasses.is_dataclass(nxt):
            raise SystemExit(
                f"--set: {'.'.join(parts[:parts.index(p) + 1])!r} is not "
                f"configured on this preset (value: {nxt!r})")
        objs.append(nxt)
    leaf = parts[-1]
    fields = {f.name: f for f in dataclasses.fields(objs[-1])}
    if leaf not in fields:
        raise SystemExit(f"--set: {path!r} not found on this preset")
    ann = str(fields[leaf].type)
    m = re.match(r"[A-Za-z_]+", ann.strip())
    primary = m.group(0) if m else ann
    if primary not in ("bool", "int", "float", "str"):
        # Checked FIRST so optional non-scalar subtrees (e.g.
        # `gossip: GossipConfig | None`) can't be nulled via the
        # none/null branch and crash later.
        raise SystemExit(
            f"--set: field {path!r} of type {ann!r} is not settable "
            "from the CLI")
    if raw.lower() in ("none", "null") and "None" in ann:
        val = None
    elif primary == "bool":
        low = raw.lower()
        if low in ("1", "true", "yes"):
            val = True
        elif low in ("0", "false", "no"):
            val = False
        else:
            raise SystemExit(
                f"--set: {path!r} is a bool; use true/false, got {raw!r}")
    elif primary == "int":
        try:
            val = int(raw)
        except ValueError:
            raise SystemExit(f"--set: {path!r} expects an int, got {raw!r}")
    elif primary == "float":
        try:
            val = float(raw)
        except ValueError:
            raise SystemExit(f"--set: {path!r} expects a float, got {raw!r}")
    else:  # primary == "str"
        val = raw
    new = dataclasses.replace(objs[-1], **{leaf: val})
    for obj, name in zip(reversed(objs[:-1]), reversed(parts[:-1])):
        new = dataclasses.replace(obj, **{name: new})
    return new


def build_trainer(cfg):
    if cfg.backend not in ("jax", "torch"):
        raise ValueError(
            f"unknown backend {cfg.backend!r}; 'jax' (TPU/mesh engines) or "
            "'torch' (the sequential reference oracle)")
    if cfg.backend == "torch":
        from dopt.engine.torch_backend import build_torch_trainer

        return build_torch_trainer(cfg)
    from dopt.engine import FederatedTrainer, GossipTrainer, SeqLMTrainer

    if cfg.seqlm is not None:
        return SeqLMTrainer(cfg)
    if cfg.federated is not None:
        return FederatedTrainer(cfg)
    return GossipTrainer(cfg)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", required=True,
                    help="preset name (see dopt.presets.PRESETS) or 'list'")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override round count")
    ap.add_argument("--num-users", type=int, default=None)
    ap.add_argument("--synthetic-scale", type=float, default=None,
                    help="scale synthetic dataset sizes (e.g. 0.1 for smoke)")
    ap.add_argument("--csv", default=None, help="write history CSV here")
    ap.add_argument("--checkpoint", default=None,
                    help="save a checkpoint here after the run")
    ap.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                    help="auto-checkpoint to --checkpoint every K rounds "
                         "during the run (crash-exact: a run killed at any "
                         "point and restarted with --resume is bit-identical "
                         "to a continuous run); federated/gossip jax "
                         "engines only")
    ap.add_argument("--resume", default=None,
                    help="restore this checkpoint before running (pair with "
                         "--checkpoint-every for kill-and-resume workflows)")
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    help="inject deterministic faults "
                         "(dopt.faults.FaultPlan): comma-separated "
                         "FaultConfig fields, e.g. "
                         "'crash=0.1,straggle=0.2,straggle_frac=0.5,"
                         "partition=0.05' or the lossy-link/elastic knobs "
                         "'msg_drop=0.1,msg_delay=0.2,msg_delay_max=2,"
                         "churn=0.02,churn_span=4'; every injected fault is "
                         "recorded in the run's fault ledger.  Pair "
                         "asymmetric msg_drop with --set "
                         "gossip.correction=push_sum (bias-free consensus) "
                         "and msg_delay/straggler drops with --set "
                         "federated.staleness_max=K (late updates admitted "
                         "with decay instead of lost)")
    ap.add_argument("--corrupt", default=None, metavar="SPEC",
                    help="inject Byzantine corruption (workers that LIE): "
                         "'p=0.25,mode=signflip,scale=50,max=2' or a bare "
                         "probability; merges onto --faults so crash and "
                         "corruption compose.  modes: nan|inf|scale|"
                         "signflip|stale; with p=1 'max=f' pins workers "
                         "0..f-1 as persistent adversaries")
    ap.add_argument("--aggregator", default=None,
                    choices=["mean", "trimmed_mean", "median", "krum",
                             "multi_krum"],
                    help="Byzantine-robust aggregation (dopt.robust): how "
                         "the federated server combines surviving updates "
                         "(default mean).  Tune the knobs with --set "
                         "robust.trim_frac=... etc.; the gossip engine's "
                         "defense is clipped gossip: pass "
                         "'--aggregator mean --set robust.clip_radius=R' "
                         "(the flag installs the robust section)")
    ap.add_argument("--clients", type=int, default=None, metavar="N",
                    help="client population registry (dopt.population): "
                         "sample each round's cohort from N host-side "
                         "client records instead of equating workers with "
                         "device lanes; the cohort trains in "
                         "ceil(cohort/lanes) waves with hierarchical "
                         "(bucketed reduce-scatter) aggregation.  Pair "
                         "with --cohort/--cohort-seed; tune the lane "
                         "width with --set population.lanes=W")
    ap.add_argument("--cohort", type=int, default=None, metavar="M",
                    help="clients sampled per round (default 64; requires "
                         "--clients or a population preset)")
    ap.add_argument("--cohort-seed", type=int, default=None, metavar="S",
                    help="cohort-sampler seed (default: the experiment "
                         "seed); draws are stateless per (seed, round)")
    ap.add_argument("--faults-json", default=None, metavar="PATH",
                    help="write the run's fault ledger here as JSON")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="stream structured telemetry (dopt.obs) to this "
                         "JSONL file: one versioned event per line — "
                         "per-round 'round' events (the history row), "
                         "typed 'fault' events (the ledger), and 'gauge' "
                         "events (quarantine/staleness/population state, "
                         "end-of-run consensus distance).  With --resume "
                         "the stream APPENDS and continues from its round "
                         "watermark (no duplicated or missing rounds); "
                         "validate with 'python -m dopt.obs.check PATH'")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome-trace/Perfetto JSON of the host "
                         "spans (batch planning, fused block dispatches, "
                         "checkpoint writes) here — the host-side "
                         "companion to the XLA trace from --trace")
    ap.add_argument("--diagnostics", choices=("off", "on"), default=None,
                    help="per-round on-device convergence diagnostics "
                         "(GossipConfig/FederatedConfig.diagnostics): "
                         "'on' emits update/grad/param norms, lane-loss "
                         "spread and the per-round consensus distance / "
                         "lane dispersion as deterministic gauges, plus "
                         "HBM 'resource' samples and 'compile' retrace "
                         "events, into --metrics-out; default keeps the "
                         "preset's setting ('off' = the exact pre-change "
                         "programs)")
    ap.add_argument("--timers", action="store_true",
                    help="print phase-timer report")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="capture a jax/XLA profiler trace of the run "
                         "into DIR (view with tensorboard or xprof)")
    ap.add_argument("--set", action="append", default=[], metavar="PATH=VAL",
                    dest="overrides",
                    help="override any config field by dotted path, e.g. "
                         "--set gossip.topology=hierarchical "
                         "--set optim.lr=0.05 --set seed=7; value is coerced "
                         "to the field's annotated type; for optional "
                         "fields (e.g. gossip.comm_dtype) the literal "
                         "strings 'none'/'null' set the field to None — "
                         "they cannot be passed as string values there")
    args = ap.parse_args(argv)

    from dopt.presets import PRESETS, get_preset

    if args.preset == "list":
        for name in sorted(PRESETS):
            print(name)
        return 0

    cfg = get_preset(args.preset)
    if args.aggregator:
        # Installed BEFORE --set so `--aggregator krum --set
        # robust.krum_f=2` works on presets without a robust section.
        from dopt.config import RobustConfig

        base = cfg.robust or RobustConfig()
        cfg = cfg.replace(
            robust=dataclasses.replace(base, aggregator=args.aggregator))
    for spec in args.overrides:
        cfg = apply_override(cfg, spec)
    if args.faults:
        from dopt.faults import parse_fault_spec

        try:
            cfg = cfg.replace(faults=parse_fault_spec(args.faults))
        except ValueError as e:
            raise SystemExit(str(e))
    if args.corrupt:
        from dopt.faults import parse_corrupt_spec

        try:
            cfg = cfg.replace(
                faults=parse_corrupt_spec(args.corrupt, base=cfg.faults))
        except ValueError as e:
            raise SystemExit(str(e))
    if cfg.faults is not None and (cfg.seqlm is not None
                                   or cfg.backend == "torch"):
        # The torch oracle and seqlm engines never read cfg.faults —
        # reject loudly instead of running "fault-free" with an empty
        # ledger the user believes is a faulted run.
        raise SystemExit("fault injection is supported by the "
                         "federated/gossip jax engines only")
    if (args.clients is not None or args.cohort is not None
            or args.cohort_seed is not None):
        from dopt.config import PopulationConfig
        from dopt.population import validate_population_config

        base_pop = cfg.population
        if args.clients is None and base_pop is None:
            raise SystemExit("--cohort/--cohort-seed need --clients N (or "
                             "a preset with a population section)")
        pop_kw = {}
        if args.clients is not None:
            pop_kw["clients"] = args.clients
        if args.cohort is not None:
            pop_kw["cohort"] = args.cohort
        if args.cohort_seed is not None:
            pop_kw["seed"] = args.cohort_seed
        pop = dataclasses.replace(base_pop or PopulationConfig(), **pop_kw)
        try:
            validate_population_config(pop)
        except ValueError as e:
            raise SystemExit(str(e))
        cfg = cfg.replace(population=pop)
    if cfg.population is not None and (cfg.seqlm is not None
                                       or cfg.backend == "torch"):
        # Same contract as faults: the torch oracle and seqlm engines
        # never read cfg.population — reject instead of silently running
        # the classic worker==lane experiment.
        raise SystemExit("the client population registry is supported by "
                         "the federated/gossip jax engines only")
    if args.diagnostics is not None:
        if cfg.gossip is not None:
            cfg = cfg.replace(gossip=dataclasses.replace(
                cfg.gossip, diagnostics=args.diagnostics))
        elif cfg.federated is not None:
            cfg = cfg.replace(federated=dataclasses.replace(
                cfg.federated, diagnostics=args.diagnostics))
        else:
            # Same contract as --faults/--metrics-out: the torch oracle
            # and seqlm engines carry no diagnostics layer.
            raise SystemExit("--diagnostics is supported by the "
                             "federated/gossip jax engines only")
    if args.num_users is not None:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data,
                                                   num_users=args.num_users))
    if args.synthetic_scale is not None:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data,
            synthetic_train_size=max(int(cfg.data.synthetic_train_size
                                         * args.synthetic_scale),
                                     cfg.data.num_users * 8),
            synthetic_test_size=max(int(cfg.data.synthetic_test_size
                                        * args.synthetic_scale), 64),
        ))

    from dopt.config import exp_details

    print(exp_details(cfg), file=sys.stderr)
    trainer = build_trainer(cfg)
    if args.resume:
        trainer.restore(args.resume)
        print(f"resumed at round {trainer.round}", file=sys.stderr)

    tele = None
    if args.metrics_out or args.trace_out:
        if cfg.seqlm is not None or cfg.backend == "torch":
            # Same contract as --faults: only the federated/gossip jax
            # engines carry the emission sites — reject instead of
            # writing an empty stream the user believes is telemetry.
            raise SystemExit("--metrics-out/--trace-out are supported by "
                             "the federated/gossip jax engines only")
        from dopt.obs import Telemetry, attach

        tele = (Telemetry.to_jsonl(args.metrics_out,
                                   resume=bool(args.resume))
                if args.metrics_out else Telemetry())
        attach(trainer, tele,
               checkpoint_every=args.checkpoint_every or None)

    rounds = args.rounds
    if rounds is None:
        if cfg.seqlm is not None:
            rounds = cfg.seqlm.steps
        elif cfg.federated is not None:
            rounds = cfg.federated.rounds
        else:
            rounds = cfg.gossip.rounds
    run_kw = {}
    if args.checkpoint_every:
        if not args.checkpoint:
            raise SystemExit("--checkpoint-every requires --checkpoint PATH")
        if cfg.seqlm is not None or cfg.backend == "torch":
            raise SystemExit("--checkpoint-every is supported by the "
                             "federated/gossip jax engines only")
        run_kw = {"checkpoint_every": args.checkpoint_every,
                  "checkpoint_path": args.checkpoint}
    if args.trace:
        from dopt.utils.profiling import trace

        with trace(args.trace):
            trainer.run(rounds=rounds, **run_kw)
        print(f"wrote XLA trace to {args.trace}", file=sys.stderr)
    else:
        trainer.run(rounds=rounds, **run_kw)
    for row in trainer.history.rows[-min(rounds, len(trainer.history)):]:
        print(json.dumps(row))
    print(f"total_time_s={trainer.total_time:.2f}", file=sys.stderr)

    if args.timers:
        print(trainer.timers.report(), file=sys.stderr)
    if args.csv:
        trainer.history.to_csv(args.csv)
        print(f"wrote {args.csv}", file=sys.stderr)
    if getattr(trainer.history, "faults", None):
        print(f"fault ledger: {len(trainer.history.faults)} entries",
              file=sys.stderr)
    if args.faults_json:
        trainer.history.faults_to_json(args.faults_json)
        print(f"wrote fault ledger to {args.faults_json}", file=sys.stderr)
    if args.checkpoint:
        trainer.save(args.checkpoint)
        print(f"checkpointed to {args.checkpoint}", file=sys.stderr)
    if tele is not None:
        # Closed AFTER the final --checkpoint save: the engines emit a
        # `checkpoint` telemetry event when a save lands, and a closed
        # sink would turn the last one into an I/O error.
        tele.close()
        if args.metrics_out:
            print(f"wrote telemetry stream to {args.metrics_out}",
                  file=sys.stderr)
        if args.trace_out:
            tele.write_trace(args.trace_out)
            print(f"wrote host span trace to {args.trace_out}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
