"""The local-training step: per-worker SGD epochs as a ``lax.scan``.

This is the reference's inner hot loop (``Client.update_weights``,
``Decentralized Optimization/src/clients.py:36-53`` /
``Client.local_update``, ``Distributed Optimization/src/clients.py:34-59``)
turned into a pure function: given a worker's params + momentum and a
[S, B, ...] batch stack (S = local_ep × steps_per_epoch from the batch
plan), scan SGD steps and return the new state plus per-step metrics.

``make_local_update`` builds the per-worker function; ``vmap`` over the
leading worker axis turns it into the stacked-engine step.  FedProx and
FedADMM enter as gradient edits (``dopt.optim``), with the global model
``theta`` broadcast (in_axes=None) and the ADMM duals stacked per
worker — the dual variables are worker-sharded pytrees, exactly the
TPU mapping SURVEY §2.3 calls for.
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import jax.numpy as jnp

from dopt.models.losses import (accuracy, accuracy_stacked, cross_entropy,
                                cross_entropy_stacked, l2_regulariser,
                                l2_stacked)
from dopt.optim import (SGDState, admm_grad_edit, clip_by_global_norm,
                        clip_by_global_norm_stacked, prox_grad_edit,
                        scaffold_grad_edit, sgd_step)

# Unroll factor for the inner SGD-step scans: each lax.while iteration
# carries fixed loop bookkeeping (measured ~7% of headline device time
# as `while` self-time); unrolling amortises it over k steps at the
# price of a k-times-larger loop body to compile.  Exposed as an env
# knob for benchmarking; 1 = plain scan.
_SCAN_UNROLL = int(os.environ.get("DOPT_SCAN_UNROLL", "1"))


def validate_optimizer(cfg) -> None:
    """Only 'sgd' exists (the reference's single optimizer,
    clients.py:14); anything else fails loudly at trainer construction
    rather than silently running SGD."""
    if cfg.optim.optimizer.lower() != "sgd":
        raise ValueError(
            f"unknown optimizer {cfg.optim.optimizer!r}: only 'sgd' "
            "exists (the reference's single optimizer, clients.py:14)")


def prepare_holdout(cfg, index_matrix, mesh, *, batch_size):
    """Shared trainer setup for the reference's local train/val holdout
    (``train_val_test`` — P1 clients.py:16-34 / P2 clients.py:19-32).

    Returns ``(use_holdout, train_matrix, (vidx_dev, vw_dev))``: the
    training index matrix (the full shard when the holdout is off) and
    per-worker local-val eval stacks placed with the worker axis sharded.
    When off, the val stacks are [W, 1, 1] zero dummies so jitted round
    signatures stay static either way — both engines rely on that
    contract."""
    import numpy as np

    from dopt.data import holdout_split, stacked_eval_batches
    from dopt.parallel.mesh import worker_sharding

    w = index_matrix.shape[0]
    use = cfg.data.local_holdout > 0.0
    if use:
        train_matrix, val_matrix = holdout_split(
            index_matrix, fraction=cfg.data.local_holdout,
            mode=cfg.data.holdout_mode, seed=cfg.seed)
        vi, vw = stacked_eval_batches(val_matrix, batch_size=batch_size)
    else:
        train_matrix = index_matrix
        vi = np.zeros((w, 1, 1), np.int32)
        vw = np.zeros((w, 1, 1), np.float32)
    sh = worker_sharding(mesh)
    return use, train_matrix, (jax.device_put(vi, sh), jax.device_put(vw, sh))


def _apply_update(p, m, g, *, lr, momentum, update_impl):
    """Dispatch the momentum-SGD update: 'jnp' (tree.map two-liner) or
    'pallas' (fused single-pass kernel, dopt.ops.fused_update).

    The ``dopt_update`` named scope tags the update's HLO ops so the
    profiler can attribute the round's device time into conv / mixing-
    comm / update fractions (``dopt.utils.profiling.classify_phase``,
    surfaced in bench.py's JSON line) — metadata only, numerics and
    compiled programs are unchanged."""
    with jax.named_scope("dopt_update"):
        if update_impl == "pallas":
            from dopt.ops import fused_sgd_momentum_tree

            return fused_sgd_momentum_tree(p, m, g, lr=lr, mu=momentum)
        p, st = sgd_step(p, SGDState(m), g, lr=lr, momentum=momentum)
        return p, st.momentum


def _make_step_core(apply_fn, *, lr, momentum, algorithm, rho, l2,
                    update_impl, clip_norm=0.0):
    """One SGD step on concrete batch arrays — the shared body of both
    local-update variants (materialised batches and on-device gather)."""

    def step_core(p, m, x, y, w, theta=None, alpha=None):
        def loss_fn(p_):
            out = apply_fn({"params": p_}, x)
            loss = cross_entropy(out, y, w)
            if l2:
                loss = loss + l2_regulariser(p_, l2)
            return loss, out

        (loss, out), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        if algorithm == "fedprox":
            g = prox_grad_edit(g, p, theta, rho)
        elif algorithm == "fedadmm":
            g = admm_grad_edit(g, p, theta, alpha, rho)
        elif algorithm == "scaffold":
            # theta slot carries the server control variate c (broadcast),
            # alpha slot the client control variate c_i (worker-stacked).
            g = scaffold_grad_edit(g, theta, alpha)
        if clip_norm:
            g = clip_by_global_norm(g, clip_norm)
        p, m = _apply_update(p, m, g, lr=lr, momentum=momentum,
                             update_impl=update_impl)
        return p, m, loss, accuracy(out, y, w)

    return step_core


def _make_stacked_step_core(stacked_apply, *, lr, momentum, algorithm, rho,
                            l2, update_impl, clip_norm=0.0):
    """One SGD step on the FULL [W, B, ...] stacked batch without vmap —
    the grouped-conv fast path (``dopt.models.make_stacked_apply``).

    Gradient identity with the vmapped core: workers are independent, so
    ∂(Σ_w loss_w)/∂p_w = ∂loss_w/∂p_w — differentiating the summed loss
    over the stacked pytree yields exactly each worker's own gradient.
    The per-worker grad edits broadcast naturally (theta leaves [...] vs
    stacked leaves [W, ...]).  Returns per-worker [W] loss/acc rows like
    one vmapped step.
    """

    def step_core(p, m, x, y, w, theta=None, alpha=None):
        def loss_fn(p_):
            out = stacked_apply(p_, x)
            lw = cross_entropy_stacked(out, y, w)
            if l2:
                lw = lw + l2_stacked(p_, l2)
            return lw.sum(), (out, lw)

        (_, (out, lw)), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        if algorithm == "fedprox":
            g = prox_grad_edit(g, p, theta, rho)
        elif algorithm == "fedadmm":
            g = admm_grad_edit(g, p, theta, alpha, rho)
        elif algorithm == "scaffold":
            g = scaffold_grad_edit(g, theta, alpha)
        if clip_norm:
            g = clip_by_global_norm_stacked(g, clip_norm)
        p, m = _apply_update(p, m, g, lr=lr, momentum=momentum,
                             update_impl=update_impl)
        return p, m, lw, accuracy_stacked(out, y, w)

    return step_core


def _gate_tree(gate, new, old):
    """``new`` where ``gate`` else ``old``, per leaf.  ``gate`` is a
    scalar bool (per-worker vmapped cores) or a [W] bool vector (stacked
    cores), broadcast over each leaf's trailing dims.  The straggler
    deadline model (``dopt.faults``) uses this to freeze a worker's
    params/momentum once its per-round work budget is spent — static
    shapes, no dynamic slicing, dead-cheap when every gate is on."""
    def sel(a, b):
        g = gate
        if getattr(g, "ndim", 0):
            g = g.reshape(g.shape + (1,) * (a.ndim - g.ndim))
        return jnp.where(g, a, b)

    return jax.tree.map(sel, new, old)


def make_local_update(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
    clip_norm: float = 0.0,
    with_limit: bool = False,
):
    """Build the per-worker local-update function.

    algorithm: 'sgd' (FedAvg / D-SGD local step), 'fedprox', 'fedadmm',
    'scaffold' (theta slot = server control c, alpha slot = client c_i).
    Returns fn(params, mom, bx, by, bw, theta=None, alpha=None) ->
    (new_params, new_mom, losses[S], accs[S]).

    ``with_limit=True`` builds the straggler-deadline variant instead:
    fn(params, mom, bx, by, bw, limit, theta=None, alpha=None) where
    ``limit`` is this worker's SGD-step budget — steps i >= limit leave
    params/momentum frozen (per-step metrics are still emitted; rows
    past the limit reflect the frozen params).  ``limit = S`` is
    bit-identical to the unlimited variant.
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl, clip_norm=clip_norm)

    if with_limit:
        def local_update_lim(params, mom, bx, by, bw, limit,
                             theta=None, alpha=None):
            steps = jnp.arange(bx.shape[0])

            def step(carry, batch):
                p, m = carry
                x, y, w, i = batch
                p2, m2, loss, acc = core(p, m, x, y, w, theta, alpha)
                g = i < limit
                return (_gate_tree(g, p2, p), _gate_tree(g, m2, m)), (loss, acc)

            (params, mom), (losses, accs) = jax.lax.scan(
                step, (params, mom), (bx, by, bw, steps))
            return params, mom, losses, accs

        return local_update_lim

    def local_update(params, mom, bx, by, bw, theta=None, alpha=None):
        def step(carry, batch):
            p, m = carry
            x, y, w = batch
            p, m, loss, acc = core(p, m, x, y, w, theta, alpha)
            return (p, m), (loss, acc)

        (params, mom), (losses, accs) = jax.lax.scan(step, (params, mom), (bx, by, bw))
        return params, mom, losses, accs

    return local_update


def _arity_wrap(algorithm, fn):
    """Give the grouped-stacked update the same per-algorithm call arity
    as its vmapped twin (callers pass theta/alpha positionally)."""
    if algorithm == "sgd":
        return lambda *a: fn(*a)
    if algorithm == "fedprox":
        return lambda *a: fn(*a[:-1], theta=a[-1])
    return lambda *a: fn(*a[:-2], theta=a[-2], alpha=a[-1])


def make_stacked_local_update(apply_fn, *, lr, momentum, algorithm="sgd",
                              rho=0.0, l2=0.0, update_impl="jnp",
                              stacked_apply=None, clip_norm=0.0,
                              with_limit=False):
    """vmap the per-worker update over the leading worker axis — or,
    with ``stacked_apply`` set (``dopt.models.make_stacked_apply``), run
    the grouped-conv stacked step with NO vmap: the scan iterates over
    S-major batches and every step consumes the full [W, B, ...] slab.

    theta (global model) is broadcast; alpha (ADMM duals) is stacked.
    ``with_limit=True`` builds the straggler-deadline variant: a [W]
    int ``limit`` rides after ``bw`` and worker w's params/momentum
    freeze from step limit[w] on (``make_local_update``).
    """
    if stacked_apply is not None:
        core = _make_stacked_step_core(
            stacked_apply, lr=lr, momentum=momentum, algorithm=algorithm,
            rho=rho, l2=l2, update_impl=update_impl, clip_norm=clip_norm)

        if with_limit:
            def fn_lim(p, m, bx, by, bw, limit, theta=None, alpha=None):
                xs = (bx.swapaxes(0, 1), by.swapaxes(0, 1),
                      bw.swapaxes(0, 1), jnp.arange(bx.shape[1]))

                def step(carry, batch):
                    p_, m_ = carry
                    x, y, w, i = batch
                    p2, m2, lw, aw = core(p_, m_, x, y, w, theta, alpha)
                    g = i < limit
                    return (_gate_tree(g, p2, p_),
                            _gate_tree(g, m2, m_)), (lw, aw)

                (p, m), (losses, accs) = jax.lax.scan(step, (p, m), xs)
                return p, m, losses.swapaxes(0, 1), accs.swapaxes(0, 1)

            return _arity_wrap(algorithm, fn_lim)

        def fn(p, m, bx, by, bw, theta=None, alpha=None):
            xs = (bx.swapaxes(0, 1), by.swapaxes(0, 1), bw.swapaxes(0, 1))

            def step(carry, batch):
                p_, m_ = carry
                x, y, w = batch
                p_, m_, lw, aw = core(p_, m_, x, y, w, theta, alpha)
                return (p_, m_), (lw, aw)

            (p, m), (losses, accs) = jax.lax.scan(step, (p, m), xs)
            return p, m, losses.swapaxes(0, 1), accs.swapaxes(0, 1)

        return _arity_wrap(algorithm, fn)
    fn = make_local_update(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl, clip_norm=clip_norm,
                           with_limit=with_limit)
    if with_limit:
        if algorithm == "sgd":
            return jax.vmap(
                lambda p, m, bx, by, bw, lim: fn(p, m, bx, by, bw, lim))
        if algorithm == "fedprox":
            return jax.vmap(
                lambda p, m, bx, by, bw, lim, theta: fn(
                    p, m, bx, by, bw, lim, theta=theta),
                in_axes=(0, 0, 0, 0, 0, 0, None),
            )
        return jax.vmap(
            lambda p, m, bx, by, bw, lim, theta, alpha: fn(
                p, m, bx, by, bw, lim, theta=theta, alpha=alpha),
            in_axes=(0, 0, 0, 0, 0, 0, None, 0),
        )
    if algorithm == "sgd":
        return jax.vmap(lambda p, m, bx, by, bw: fn(p, m, bx, by, bw))
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, bx, by, bw, theta: fn(p, m, bx, by, bw, theta=theta),
            in_axes=(0, 0, 0, 0, 0, None),
        )
    return jax.vmap(
        lambda p, m, bx, by, bw, theta, alpha: fn(p, m, bx, by, bw,
                                                  theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, 0, None, 0),
    )


def flat_input_apply(apply_fn, sample_shape):
    """Wrap a flax ``apply`` so it accepts FLAT feature rows and
    reshapes them to the model's input shape at use.

    The engines keep the resident train arrays flat ([N, F] instead of
    [N, H, W, C]) because TPU row-gathers from an [N, 28, 28, 1] array
    run ~2.6× slower end-to-end than from [N, 784] and the C=1-minor
    layout additionally poisons the layouts of everything computed from
    the gathered slab (measured on v5e: 1.42 → 0.55 ms/step on the
    headline workload).  A no-op when the rows are already shaped.
    """
    def wrapped(variables, x):
        return apply_fn(variables, x.reshape(x.shape[0], *sample_shape))

    return wrapped


def flat_input_stacked_apply(stacked_apply, sample_shape):
    """``flat_input_apply`` for the grouped stacked forward
    ([W, B, F] flat rows → [W, B, *sample_shape])."""
    def wrapped(params, x):
        return stacked_apply(params, x.reshape(*x.shape[:2], *sample_shape))

    return wrapped


def pick_gather_chunks(steps: int, *, workers: int, batch: int,
                       sample_bytes: int,
                       budget_bytes: int = 256 * 1024 * 1024) -> int | None:
    """Choose how many chunks to split a [S, B] plan into so each chunk's
    materialised batch slab ([W, S/k, B, sample]) fits ``budget_bytes``.

    Rationale: gathering one minibatch per step inside the scan costs
    ~250 µs of fixed gather overhead per step on a v5e (18% of device
    time on the headline workload, results/trace_headline.json); one big
    gather per chunk runs at memcpy speed.  Returns the smallest divisor
    of ``steps`` whose slab fits, or None (meaning: keep the per-step
    gather) when even per-step slabs would blow the budget — which
    cannot happen in practice since k=steps is always a divisor.
    """
    for k in range(1, steps + 1):
        if steps % k:
            continue
        if workers * (steps // k) * batch * sample_bytes <= budget_bytes:
            return k
    return None


def _scan_steps_gathered(core, params, mom, idx, bw, train_x, train_y,
                         theta, alpha, gather_chunks, limit=None):
    """Scan SGD steps over a [S, B] index plan against the resident train
    arrays.  ``gather_chunks=None`` gathers each minibatch inside the
    step body (O(B·|x|) live memory, one small gather per step);
    ``gather_chunks=k`` splits S into k chunks and materialises each
    chunk's batches with ONE big gather (O((S/k)·B·|x|) live memory) —
    same indices, same order, bit-identical numerics, far less per-step
    gather overhead.  ``limit`` (straggler deadline) carries a step
    counter and freezes params/momentum from step ``limit`` on."""

    gated = limit is not None

    def step(carry, batch):
        if gated:
            p, m, k = carry
            x, y, w = batch
            p2, m2, loss, acc = core(p, m, x, y, w, theta, alpha)
            g = k < limit
            return (_gate_tree(g, p2, p), _gate_tree(g, m2, m),
                    k + 1), (loss, acc)
        p, m = carry
        x, y, w = batch
        p, m, loss, acc = core(p, m, x, y, w, theta, alpha)
        return (p, m), (loss, acc)

    carry0 = ((params, mom, jnp.zeros((), jnp.int32)) if gated
              else (params, mom))

    def strip(carry):
        return carry[:2] if gated else carry

    if gather_chunks is None:
        def gstep(carry, batch):
            i, w = batch
            return step(carry, (train_x[i], train_y[i], w))

        carry, out = jax.lax.scan(gstep, carry0, (idx, bw))
        return strip(carry), out

    s, b = idx.shape
    if s % gather_chunks:
        raise ValueError(
            f"gather_chunks={gather_chunks} does not divide steps={s}")
    idx_c = idx.reshape(gather_chunks, s // gather_chunks, b)
    bw_c = bw.reshape(gather_chunks, s // gather_chunks, b)

    def chunk(carry, ch):
        ci, cw = ch
        return jax.lax.scan(step, carry, (train_x[ci], train_y[ci], cw))

    carry, (losses, accs) = jax.lax.scan(chunk, carry0, (idx_c, bw_c))
    return strip(carry), (losses.reshape(s), accs.reshape(s))


def make_local_update_gather(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
    gather_chunks: int | None = None,
    clip_norm: float = 0.0,
    with_limit: bool = False,
):
    """Like ``make_local_update`` but gathers minibatches from the full
    on-device dataset inside the scan: the caller passes the [S, B]
    index/weight plan plus the resident train arrays instead of
    materialised [S, B, ...] batches.  Peak activation memory drops from
    O(S·B·|x|) to O((S/k)·B·|x|) (k = ``gather_chunks``; None = one
    small gather per step, O(B·|x|)), which is what lets the fused
    multi-round block path keep K rounds of plans on device at once.

    Returns fn(params, mom, idx, bw, train_x, train_y, theta=None,
    alpha=None) -> (new_params, new_mom, losses[S], accs[S]); with
    ``with_limit=True`` the straggler step budget rides after ``bw``:
    fn(params, mom, idx, bw, limit, train_x, train_y, ...).
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl, clip_norm=clip_norm)

    if with_limit:
        def local_update_lim(params, mom, idx, bw, limit, train_x, train_y,
                             theta=None, alpha=None):
            (params, mom), (losses, accs) = _scan_steps_gathered(
                core, params, mom, idx, bw, train_x, train_y, theta, alpha,
                gather_chunks, limit=limit)
            return params, mom, losses, accs

        return local_update_lim

    def local_update(params, mom, idx, bw, train_x, train_y,
                     theta=None, alpha=None):
        (params, mom), (losses, accs) = _scan_steps_gathered(
            core, params, mom, idx, bw, train_x, train_y, theta, alpha,
            gather_chunks)
        return params, mom, losses, accs

    return local_update


def _scan_steps_gathered_stacked(core, params, mom, idx, bw, train_x,
                                 train_y, theta, alpha, gather_chunks,
                                 limit=None):
    """Stacked-core twin of ``_scan_steps_gathered``: ``idx``/``bw`` are
    [W, S, B]; the scan runs S-major and each step consumes the full
    [W, B, ...] slab.  Returns per-worker [W, S] loss/acc grids.
    ``limit`` ([W] ints, straggler deadline) freezes worker w's lanes
    from step limit[w] on."""
    idx_s = idx.swapaxes(0, 1)   # [S, W, B]
    bw_s = bw.swapaxes(0, 1)
    gated = limit is not None

    def step(carry, batch):
        if gated:
            p, m, k = carry
            x, y, w = batch
            p2, m2, lw, aw = core(p, m, x, y, w, theta, alpha)
            g = k < limit      # [W] bool
            return (_gate_tree(g, p2, p), _gate_tree(g, m2, m),
                    k + 1), (lw, aw)
        p, m = carry
        x, y, w = batch
        p, m, lw, aw = core(p, m, x, y, w, theta, alpha)
        return (p, m), (lw, aw)

    carry0 = ((params, mom, jnp.zeros((), jnp.int32)) if gated
              else (params, mom))

    def strip(carry):
        return carry[:2] if gated else carry

    if gather_chunks is None:
        def gstep(carry, batch):
            i, w = batch
            return step(carry, (train_x[i], train_y[i], w))

        carry, (losses, accs) = jax.lax.scan(gstep, carry0,
                                             (idx_s, bw_s),
                                             unroll=_SCAN_UNROLL)
        return strip(carry), (losses.swapaxes(0, 1), accs.swapaxes(0, 1))

    s = idx_s.shape[0]
    if s % gather_chunks:
        raise ValueError(
            f"gather_chunks={gather_chunks} does not divide steps={s}")
    idx_c = idx_s.reshape(gather_chunks, s // gather_chunks, *idx_s.shape[1:])
    bw_c = bw_s.reshape(idx_c.shape)

    def chunk(carry, ch):
        ci, cw = ch
        return jax.lax.scan(step, carry, (train_x[ci], train_y[ci], cw),
                            unroll=_SCAN_UNROLL)

    carry, (losses, accs) = jax.lax.scan(chunk, carry0, (idx_c, bw_c))
    w_ = idx.shape[0]
    return strip(carry), (losses.reshape(s, w_).swapaxes(0, 1),
                          accs.reshape(s, w_).swapaxes(0, 1))


def make_stacked_local_update_gather(apply_fn, *, lr, momentum,
                                     algorithm="sgd", rho=0.0, l2=0.0,
                                     update_impl="jnp",
                                     gather_chunks=None,
                                     stacked_apply=None, clip_norm=0.0,
                                     with_limit=False):
    """vmap the gather-variant over the leading worker axis; train arrays
    and theta broadcast, ADMM duals stacked per worker.  With
    ``stacked_apply`` set, the grouped-conv stacked path replaces the
    vmap (see ``make_stacked_local_update``).  ``with_limit=True``: a
    [W] straggler step budget rides after ``bw``."""
    if stacked_apply is not None:
        core = _make_stacked_step_core(
            stacked_apply, lr=lr, momentum=momentum, algorithm=algorithm,
            rho=rho, l2=l2, update_impl=update_impl, clip_norm=clip_norm)

        if with_limit:
            def fn_lim(p, m, idx, bw, limit, tx, ty, theta=None, alpha=None):
                (p, m), (losses, accs) = _scan_steps_gathered_stacked(
                    core, p, m, idx, bw, tx, ty, theta, alpha,
                    gather_chunks, limit=limit)
                return p, m, losses, accs

            return _arity_wrap(algorithm, fn_lim)

        def fn(p, m, idx, bw, tx, ty, theta=None, alpha=None):
            (p, m), (losses, accs) = _scan_steps_gathered_stacked(
                core, p, m, idx, bw, tx, ty, theta, alpha, gather_chunks)
            return p, m, losses, accs

        return _arity_wrap(algorithm, fn)
    fn = make_local_update_gather(apply_fn, lr=lr, momentum=momentum,
                                  algorithm=algorithm, rho=rho, l2=l2,
                                  update_impl=update_impl,
                                  gather_chunks=gather_chunks,
                                  clip_norm=clip_norm,
                                  with_limit=with_limit)
    if with_limit:
        if algorithm == "sgd":
            return jax.vmap(
                lambda p, m, idx, bw, lim, tx, ty: fn(
                    p, m, idx, bw, lim, tx, ty),
                in_axes=(0, 0, 0, 0, 0, None, None),
            )
        if algorithm == "fedprox":
            return jax.vmap(
                lambda p, m, idx, bw, lim, tx, ty, theta: fn(
                    p, m, idx, bw, lim, tx, ty, theta=theta),
                in_axes=(0, 0, 0, 0, 0, None, None, None),
            )
        return jax.vmap(
            lambda p, m, idx, bw, lim, tx, ty, theta, alpha: fn(
                p, m, idx, bw, lim, tx, ty, theta=theta, alpha=alpha),
            in_axes=(0, 0, 0, 0, 0, None, None, None, 0),
        )
    if algorithm == "sgd":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty: fn(p, m, idx, bw, tx, ty),
            in_axes=(0, 0, 0, 0, None, None),
        )
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, theta: fn(p, m, idx, bw, tx, ty,
                                                    theta=theta),
            in_axes=(0, 0, 0, 0, None, None, None),
        )
    return jax.vmap(
        lambda p, m, idx, bw, tx, ty, theta, alpha: fn(
            p, m, idx, bw, tx, ty, theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, None, None, None, 0),
    )


def make_local_update_epochs(
    apply_fn: Callable,
    *,
    lr: float,
    momentum: float,
    algorithm: str = "sgd",
    rho: float = 0.0,
    l2: float = 0.0,
    update_impl: str = "jnp",
    gather_chunks: int | None = None,
    clip_norm: float = 0.0,
    with_limit: bool = False,
):
    """Local update with the reference's EPOCH structure: an outer scan
    over local epochs, each running its steps then evaluating the
    worker's local validation holdout — ``Client.update_weights``'s
    per-epoch ``inference`` + history row
    (``Decentralized Optimization/src/clients.py:38-50`` /
    ``Distributed Optimization/src/clients.py:37-57``).

    Returns fn(params, mom, idx, bw, train_x, train_y, vidx, vw,
    theta=None, alpha=None) -> (new_params, new_mom, em) where ``idx``/
    ``bw`` are [E, S', B] epoch-major plans, ``vidx``/``vw`` the [Sv, Bv]
    local-val eval stacks, and ``em`` maps per-epoch [E] arrays:

    * train_loss — mean over the epoch's batches of the batch-mean loss
      (``sum(train_loss)/len(train_loss)``, clients.py:47)
    * train_acc  — epoch correct count / train-set size
      (``train_acc += corr/total``, clients.py:44-45)
    * val_acc / val_loss_sum / val_loss_mean — post-epoch local-val
      metrics in both reference flavours (P1 ``inference`` sums batch
      losses, P2 averages them).

    ``with_limit=True`` builds the straggler-deadline variant: an EPOCH
    budget rides after ``bw`` — fn(params, mom, idx, bw, limit,
    train_x, train_y, vidx, vw, ...) — and epochs e >= limit leave
    params/momentum frozen (their em rows then reflect the frozen
    params: the straggler's val metrics stop moving at its deadline).
    """
    if algorithm not in ("sgd", "fedprox", "fedadmm", "scaffold"):
        raise ValueError(f"unknown local algorithm {algorithm!r}")
    core = _make_step_core(apply_fn, lr=lr, momentum=momentum,
                           algorithm=algorithm, rho=rho, l2=l2,
                           update_impl=update_impl, clip_norm=clip_norm)
    ev = make_evaluator(apply_fn)

    def _epoch_steps(p, m, ei, ew, train_x, train_y, theta, alpha):
        """One epoch's SGD steps: returns ((p, m), (losses, corrects,
        counts)) — shared by the unlimited and straggler-gated variants
        so their inner numerics can never diverge."""

        def step(c, b):
            p_, m_ = c
            i, w_ = b
            p_, m_, loss, acc = core(p_, m_, train_x[i], train_y[i], w_,
                                     theta, alpha)
            return (p_, m_), (loss, acc * w_.sum(), w_.sum())

        def stepm(c, b):
            p_, m_ = c
            x, y, w_ = b
            p_, m_, loss, acc = core(p_, m_, x, y, w_, theta, alpha)
            return (p_, m_), (loss, acc * w_.sum(), w_.sum())

        if gather_chunks is None:
            return jax.lax.scan(step, (p, m), (ei, ew))
        # Chunked big-gather within the epoch: same indices, same
        # order, one slab gather per chunk instead of one small
        # gather per step (see _scan_steps_gathered).
        se, bsz = ei.shape
        if se % gather_chunks:
            raise ValueError(
                f"gather_chunks={gather_chunks} does not divide "
                f"steps/epoch={se}")
        ei_c = ei.reshape(gather_chunks, se // gather_chunks, bsz)
        ew_c = ew.reshape(ei_c.shape)

        def chunk(c, ch):
            ci, cw = ch
            return jax.lax.scan(stepm, c, (train_x[ci], train_y[ci], cw))

        (p, m), (losses, corrects, counts) = jax.lax.scan(
            chunk, (p, m), (ei_c, ew_c))
        return (p, m), (losses.reshape(se), corrects.reshape(se),
                        counts.reshape(se))

    if with_limit:
        def local_update_lim(params, mom, idx, bw, limit, train_x, train_y,
                             vidx, vw, theta=None, alpha=None):
            # The unlimited epoch body with each epoch's carry gated:
            # identical inner numerics, and the single post-epoch val
            # eval sees the GATED params (a frozen straggler's val
            # metrics reflect its frozen model).
            vx = train_x[vidx]
            vy = train_y[vidx]

            def epoch(carry, ep):
                p, m = carry
                ei, ew, e = ep
                (p2, m2), (losses, corrects, counts) = _epoch_steps(
                    p, m, ei, ew, train_x, train_y, theta, alpha)
                g = e < limit
                p = _gate_tree(g, p2, p)
                m = _gate_tree(g, m2, m)
                # Train metrics for skipped epochs report 0 (the worker
                # did no work — the fault ledger records the truncation).
                vm = ev(p, vx, vy, vw)
                em = {
                    "train_loss": jnp.where(g, losses.mean(), 0.0),
                    "train_acc": jnp.where(
                        g, corrects.sum() / jnp.maximum(counts.sum(), 1.0),
                        0.0),
                    "val_acc": vm["acc"],
                    "val_loss_sum": vm["loss_sum"],
                    "val_loss_mean": vm["loss_mean"],
                }
                return (p, m), em

            (params, mom), em = jax.lax.scan(
                epoch, (params, mom),
                (idx, bw, jnp.arange(idx.shape[0])))
            return params, mom, em

        return local_update_lim

    def local_update(params, mom, idx, bw, train_x, train_y, vidx, vw,
                     theta=None, alpha=None):
        vx = train_x[vidx]
        vy = train_y[vidx]

        def epoch(carry, ep):
            p, m = carry
            ei, ew = ep
            (p, m), (losses, corrects, counts) = _epoch_steps(
                p, m, ei, ew, train_x, train_y, theta, alpha)
            vm = ev(p, vx, vy, vw)
            em = {
                "train_loss": losses.mean(),
                "train_acc": corrects.sum() / jnp.maximum(counts.sum(), 1.0),
                "val_acc": vm["acc"],
                "val_loss_sum": vm["loss_sum"],
                "val_loss_mean": vm["loss_mean"],
            }
            return (p, m), em

        (params, mom), em = jax.lax.scan(epoch, (params, mom), (idx, bw))
        return params, mom, em

    return local_update


def _stacked_eval_scan(stacked_apply, params, ex, ey, ew):
    """Eval a [W, ...]-stacked fleet over S-major [S, W, B, ...] batch
    stacks via the grouped forward; returns per-worker [W] metric dict
    (same fields as ``make_evaluator``)."""

    def step(c, b):
        x, y, w = b
        out = stacked_apply(params, x)
        loss = cross_entropy_stacked(out, y, w)
        corr = accuracy_stacked(out, y, w) * w.sum(axis=-1)
        return c, (loss, corr, w.sum(axis=-1))

    _, (losses, corrects, counts) = jax.lax.scan(step, (), (ex, ey, ew))
    total = jnp.maximum(counts.sum(axis=0), 1.0)
    return {"acc": corrects.sum(axis=0) / total,
            "loss_sum": losses.sum(axis=0),
            "loss_mean": losses.mean(axis=0), "count": total}


def make_stacked_local_update_epochs(apply_fn, *, lr, momentum,
                                     algorithm="sgd", rho=0.0, l2=0.0,
                                     update_impl="jnp", gather_chunks=None,
                                     stacked_apply=None, clip_norm=0.0,
                                     with_limit=False):
    """vmap the epoch-structured update over the leading worker axis;
    train arrays and theta broadcast, per-worker plans / val stacks /
    ADMM duals stacked.  With ``stacked_apply`` set, the grouped-conv
    stacked path replaces the vmap (see ``make_stacked_local_update``).
    ``with_limit=True``: a [W] straggler EPOCH budget rides after
    ``bw`` (see ``make_local_update_epochs``)."""
    if stacked_apply is not None:
        core = _make_stacked_step_core(
            stacked_apply, lr=lr, momentum=momentum, algorithm=algorithm,
            rho=rho, l2=l2, update_impl=update_impl, clip_norm=clip_norm)

        if with_limit:
            def fn_lim(p, m, idx, bw, elimit, tx, ty, vi, vw_,
                       theta=None, alpha=None):
                vi_s = vi.swapaxes(0, 1)
                vw_s = vw_.swapaxes(0, 1)
                vx, vy = tx[vi_s], ty[vi_s]
                idx_e = idx.swapaxes(0, 1)
                bw_e = bw.swapaxes(0, 1)

                def epoch(carry, ep):
                    p_, m_ = carry
                    ei, ew, e = ep
                    (p2, m2), (lws, aws) = _scan_steps_gathered_stacked(
                        core, p_, m_, ei, ew, tx, ty, theta, alpha,
                        gather_chunks)
                    g = e < elimit          # [W] bool epoch gate
                    p_ = _gate_tree(g, p2, p_)
                    m_ = _gate_tree(g, m2, m_)
                    counts = ew.sum(axis=-1)
                    vm = _stacked_eval_scan(stacked_apply, p_, vx, vy, vw_s)
                    em = {
                        "train_loss": jnp.where(g, lws.mean(axis=1), 0.0),
                        "train_acc": jnp.where(
                            g, (aws * counts).sum(axis=1)
                            / jnp.maximum(counts.sum(axis=1), 1.0), 0.0),
                        "val_acc": vm["acc"],
                        "val_loss_sum": vm["loss_sum"],
                        "val_loss_mean": vm["loss_mean"],
                    }
                    return (p_, m_), em

                (p, m), em = jax.lax.scan(
                    epoch, (p, m),
                    (idx_e, bw_e, jnp.arange(idx_e.shape[0])))
                em = {k: v.swapaxes(0, 1) for k, v in em.items()}  # [W, E]
                return p, m, em

            return _arity_wrap(algorithm, fn_lim)

        def fn(p, m, idx, bw, tx, ty, vi, vw_, theta=None, alpha=None):
            vi_s = vi.swapaxes(0, 1)        # [Sv, W, Bv]
            vw_s = vw_.swapaxes(0, 1)
            vx, vy = tx[vi_s], ty[vi_s]
            idx_e = idx.swapaxes(0, 1)      # [E, W, Se, B]
            bw_e = bw.swapaxes(0, 1)

            def epoch(carry, ep):
                p_, m_ = carry
                ei, ew = ep                 # [W, Se, B]
                (p_, m_), (lws, aws) = _scan_steps_gathered_stacked(
                    core, p_, m_, ei, ew, tx, ty, theta, alpha,
                    gather_chunks)
                counts = ew.sum(axis=-1)    # [W, Se]
                vm = _stacked_eval_scan(stacked_apply, p_, vx, vy, vw_s)
                em = {
                    "train_loss": lws.mean(axis=1),
                    "train_acc": ((aws * counts).sum(axis=1)
                                  / jnp.maximum(counts.sum(axis=1), 1.0)),
                    "val_acc": vm["acc"],
                    "val_loss_sum": vm["loss_sum"],
                    "val_loss_mean": vm["loss_mean"],
                }
                return (p_, m_), em

            (p, m), em = jax.lax.scan(epoch, (p, m), (idx_e, bw_e))
            em = {k: v.swapaxes(0, 1) for k, v in em.items()}  # [W, E]
            return p, m, em

        return _arity_wrap(algorithm, fn)
    fn = make_local_update_epochs(apply_fn, lr=lr, momentum=momentum,
                                  algorithm=algorithm, rho=rho, l2=l2,
                                  update_impl=update_impl,
                                  gather_chunks=gather_chunks,
                                  clip_norm=clip_norm,
                                  with_limit=with_limit)
    if with_limit:
        if algorithm == "sgd":
            return jax.vmap(
                lambda p, m, idx, bw, lim, tx, ty, vi, vw_: fn(
                    p, m, idx, bw, lim, tx, ty, vi, vw_),
                in_axes=(0, 0, 0, 0, 0, None, None, 0, 0),
            )
        if algorithm == "fedprox":
            return jax.vmap(
                lambda p, m, idx, bw, lim, tx, ty, vi, vw_, theta: fn(
                    p, m, idx, bw, lim, tx, ty, vi, vw_, theta=theta),
                in_axes=(0, 0, 0, 0, 0, None, None, 0, 0, None),
            )
        return jax.vmap(
            lambda p, m, idx, bw, lim, tx, ty, vi, vw_, theta, alpha: fn(
                p, m, idx, bw, lim, tx, ty, vi, vw_, theta=theta,
                alpha=alpha),
            in_axes=(0, 0, 0, 0, 0, None, None, 0, 0, None, 0),
        )
    if algorithm == "sgd":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, vi, vw_: fn(p, m, idx, bw, tx, ty,
                                                      vi, vw_),
            in_axes=(0, 0, 0, 0, None, None, 0, 0),
        )
    if algorithm == "fedprox":
        return jax.vmap(
            lambda p, m, idx, bw, tx, ty, vi, vw_, theta: fn(
                p, m, idx, bw, tx, ty, vi, vw_, theta=theta),
            in_axes=(0, 0, 0, 0, None, None, 0, 0, None),
        )
    return jax.vmap(
        lambda p, m, idx, bw, tx, ty, vi, vw_, theta, alpha: fn(
            p, m, idx, bw, tx, ty, vi, vw_, theta=theta, alpha=alpha),
        in_axes=(0, 0, 0, 0, None, None, 0, 0, None, 0),
    )


def make_evaluator(apply_fn):
    """Batched evaluation over a static [S, B, ...] eval stack.

    Returns fn(params, ex, ey, ew) -> dict with weighted sums so the
    caller can form either reference metric flavour:
    P1 ``inference`` returns (acc, summed-per-batch loss)
    (``Decentralized Optimization/src/clients.py:61-75``), P2 returns
    (acc, mean-per-batch loss) (``Distributed Optimization/src/clients.py:71-86``).
    """

    def evaluate(params, ex, ey, ew):
        def step(carry, batch):
            x, y, w = batch
            out = apply_fn({"params": params}, x)
            loss = cross_entropy(out, y, w)          # weighted mean over batch
            correct = accuracy(out, y, w) * w.sum()  # weighted correct count
            return carry, (loss, correct, w.sum())

        _, (losses, corrects, counts) = jax.lax.scan(step, (), (ex, ey, ew))
        total = jnp.maximum(counts.sum(), 1.0)
        return {
            "acc": corrects.sum() / total,
            "loss_sum": losses.sum(),            # P1 flavour (summed batch losses)
            "loss_mean": losses.mean(),          # P2 flavour (mean per batch)
            "count": total,
        }

    return evaluate


def make_stacked_evaluator(apply_fn, stacked_apply=None):
    """Evaluate every worker's params on the same (replicated) eval stack.
    With ``stacked_apply`` set, the grouped forward replaces the vmap
    (each eval batch is broadcast across the worker axis)."""
    if stacked_apply is not None:
        def evaluate(params, ex, ey, ew):
            w_count = jax.tree_util.tree_leaves(params)[0].shape[0]

            def step(c, b):
                x, y, w = b
                xw = jnp.broadcast_to(x[None], (w_count,) + x.shape)
                yw = jnp.broadcast_to(y[None], (w_count,) + y.shape)
                ww = jnp.broadcast_to(w[None], (w_count,) + w.shape)
                out = stacked_apply(params, xw)
                loss = cross_entropy_stacked(out, yw, ww)
                corr = accuracy_stacked(out, yw, ww) * w.sum()
                return c, (loss, corr, w.sum())

            _, (losses, corrects, counts) = jax.lax.scan(
                step, (), (ex, ey, ew))
            total = jnp.maximum(counts.sum(), 1.0)
            return {"acc": corrects.sum(axis=0) / total,
                    "loss_sum": losses.sum(axis=0),
                    "loss_mean": losses.mean(axis=0),
                    "count": jnp.full((w_count,), total)}

        return evaluate
    ev = make_evaluator(apply_fn)
    return jax.vmap(lambda p, ex, ey, ew: ev(p, ex, ey, ew),
                    in_axes=(0, None, None, None))
